"""smglint static-analysis suite + runtime guards.

Three layers, mirroring the subsystem:

1. fixture snippets per rule family — positive (fires), negative (stays
   quiet), suppressed (fires but is silenced) — so every rule's contract is
   pinned independent of the repo's current code;
2. engine mechanics — suppression forms, baseline grandfathering, CLI exit
   codes;
3. the self-lint gate: ``smglint`` over ``smg_tpu/`` reports zero
   unbaselined findings, and the runtime transfer/recompile guards hold on
   the real engine's steady-state decode loop.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

from smg_tpu.analysis import (
    LintConfig,
    apply_baseline,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
)

REPO_ROOT = Path(__file__).resolve().parent.parent

# fixtures lint under a relpath inside the configured hot set so HOTSYNC runs
HOT = "smg_tpu/engine/scheduler.py"
COLD = "smg_tpu/gateway/router.py"


def rules_of(findings, rule=None):
    hits = [f for f in findings if not f.suppressed]
    return [f.rule for f in hits if rule is None or f.rule == rule]


# ---------------------------------------------------------------- HOTSYNC

class TestHotSync:
    def test_item_fires(self):
        src = "def f(x):\n    return x.item()\n"
        assert rules_of(lint_source(src, HOT)) == ["HOTSYNC"]

    def test_bare_np_asarray_fires(self):
        src = "import numpy as np\ndef f(x):\n    return np.asarray(x)\n"
        assert rules_of(lint_source(src, HOT)) == ["HOTSYNC"]

    def test_np_asarray_with_dtype_is_host_side(self):
        src = "import numpy as np\ndef f(x):\n    return np.asarray(x, np.int32)\n"
        assert rules_of(lint_source(src, HOT)) == []

    def test_scalarized_subscript_fires(self):
        src = "def f(toks):\n    return [int(toks[0]), float(toks[1])]\n"
        assert rules_of(lint_source(src, HOT)) == ["HOTSYNC", "HOTSYNC"]

    def test_builtin_cast_of_producer_call_fires(self):
        # the gap PR 18 closes: float()/int()/bool() over a direct jnp/lax
        # producer call is one blocking fetch per element
        src = (
            "import jax.numpy as jnp\n"
            "def f(x):\n"
            "    return float(jnp.sum(x))\n"
        )
        assert rules_of(lint_source(src, HOT)) == ["HOTSYNC"]

    def test_builtin_cast_of_device_arithmetic_fires(self):
        src = (
            "import jax.numpy as jnp\n"
            "def f(a):\n"
            "    x = jnp.max(a)\n"
            "    return int(x + 1)\n"
        )
        assert rules_of(lint_source(src, HOT)) == ["HOTSYNC"]

    def test_builtin_cast_of_host_value_clean(self):
        src = "def f(xs, n):\n    return float(len(xs)) / int(n)\n"
        assert rules_of(lint_source(src, HOT)) == []

    def test_device_truthiness_fires(self):
        src = (
            "import jax.numpy as jnp\n"
            "def f(a):\n"
            "    m = jnp.equal(a, 0)\n"
            "    if m:\n"
            "        return 1\n"
        )
        assert rules_of(lint_source(src, HOT)) == ["HOTSYNC"]

    def test_device_iteration_fires(self):
        src = (
            "import jax.numpy as jnp\n"
            "def f(a):\n"
            "    out = jnp.cumsum(a)\n"
            "    return [t for t in out]\n"
        )
        # comprehension iteration is a `for` over the device name
        assert "HOTSYNC" in rules_of(lint_source(src, HOT))

    def test_print_fires(self):
        src = "def f(x):\n    print(x)\n"
        assert rules_of(lint_source(src, HOT)) == ["HOTSYNC"]

    def test_device_get_is_sanctioned(self):
        src = "import jax\ndef f(x):\n    return jax.device_get(x)\n"
        assert rules_of(lint_source(src, HOT)) == []

    def test_cold_module_exempt(self):
        src = "def f(x):\n    return x.item()\n"
        assert rules_of(lint_source(src, COLD)) == []

    def test_suppressed(self):
        src = "def f(x):\n    return x.item()  # smglint: disable=HOTSYNC why\n"
        findings = lint_source(src, HOT)
        assert [f.rule for f in findings] == ["HOTSYNC"]
        assert findings[0].suppressed


# ------------------------------------------------------------- ASYNCBLOCK

class TestAsyncBlock:
    def test_time_sleep_fires(self):
        src = "import time\nasync def f():\n    time.sleep(1)\n"
        assert rules_of(lint_source(src, COLD)) == ["ASYNCBLOCK"]

    def test_asyncio_sleep_clean(self):
        src = "import asyncio\nasync def f():\n    await asyncio.sleep(1)\n"
        assert rules_of(lint_source(src, COLD)) == []

    def test_open_fires(self):
        src = "async def f(p):\n    with open(p) as fh:\n        return fh.read()\n"
        assert rules_of(lint_source(src, COLD)) == ["ASYNCBLOCK"]

    def test_subprocess_and_urllib_fire(self):
        src = (
            "import subprocess, urllib.request\n"
            "async def f(u):\n"
            "    subprocess.run(['ls'])\n"
            "    return urllib.request.urlopen(u)\n"
        )
        assert rules_of(lint_source(src, COLD)) == ["ASYNCBLOCK", "ASYNCBLOCK"]

    def test_result_fires_and_suppresses(self):
        src = (
            "async def f(tasks):\n"
            "    # smglint: disable-next=ASYNCBLOCK tasks are done\n"
            "    return [t.result() for t in tasks]\n"
        )
        findings = lint_source(src, COLD)
        assert [f.rule for f in findings] == ["ASYNCBLOCK"]
        assert findings[0].suppressed

    def test_pathlib_io_fires(self):
        src = (
            "from pathlib import Path\n"
            "async def f(p):\n"
            "    return Path(p).read_text()\n"
        )
        assert rules_of(lint_source(src, COLD)) == ["ASYNCBLOCK"]

    def test_pathlib_io_awaited_or_offloaded_clean(self):
        src = (
            "import asyncio\n"
            "async def f(p, ap):\n"
            "    a = await ap.read_text()\n"  # anyio.Path-style async API
            "    b = await asyncio.to_thread(p.read_text)\n"  # uncalled ref
            "    return a + b\n"
        )
        assert rules_of(lint_source(src, COLD)) == []

    def test_sync_def_exempt(self):
        src = "import time\ndef f():\n    time.sleep(1)\n"
        assert rules_of(lint_source(src, COLD)) == []

    def test_nested_sync_def_exempt(self):
        # the nested def runs on whatever thread calls it (the to_thread fix)
        src = (
            "import asyncio, time\n"
            "async def f():\n"
            "    def blocking():\n"
            "        time.sleep(1)\n"
            "    await asyncio.to_thread(blocking)\n"
        )
        assert rules_of(lint_source(src, COLD)) == []


# -------------------------------------------------------------- LOCKAWAIT

_LOCK_CLASS = """
import asyncio, threading

class S:
    def __init__(self):
        self._tlock = threading.Lock()
        self._alock = asyncio.Lock()
{body}
"""


class TestLockAwait:
    def test_thread_lock_across_await_fires(self):
        src = _LOCK_CLASS.format(body=(
            "    async def f(self, coro):\n"
            "        with self._tlock:\n"
            "            await coro\n"
        ))
        assert rules_of(lint_source(src, COLD)) == ["LOCKAWAIT"]

    def test_thread_lock_without_await_clean(self):
        src = _LOCK_CLASS.format(body=(
            "    async def f(self):\n"
            "        with self._tlock:\n"
            "            self.x = 1\n"
        ))
        assert rules_of(lint_source(src, COLD)) == []

    def test_async_lock_sync_with_fires(self):
        src = _LOCK_CLASS.format(body=(
            "    def f(self):\n"
            "        with self._alock:\n"
            "            return 1\n"
        ))
        assert rules_of(lint_source(src, COLD)) == ["LOCKAWAIT"]

    def test_async_with_on_thread_lock_fires(self):
        src = _LOCK_CLASS.format(body=(
            "    async def f(self):\n"
            "        async with self._tlock:\n"
            "            return 1\n"
        ))
        assert rules_of(lint_source(src, COLD)) == ["LOCKAWAIT"]

    def test_async_lock_async_with_clean(self):
        src = _LOCK_CLASS.format(body=(
            "    async def f(self, coro):\n"
            "        async with self._alock:\n"
            "            await coro\n"
        ))
        assert rules_of(lint_source(src, COLD)) == []

    def test_thread_acquire_in_async_fires(self):
        src = _LOCK_CLASS.format(body=(
            "    async def f(self):\n"
            "        self._tlock.acquire()\n"
        ))
        assert rules_of(lint_source(src, COLD)) == ["LOCKAWAIT"]

    def test_nested_async_def_judged_by_own_asyncness(self):
        # the primary hazard hiding in a nested coroutine of a SYNC factory
        src = _LOCK_CLASS.format(body=(
            "    def make(self):\n"
            "        async def worker(coro):\n"
            "            with self._tlock:\n"
            "                await coro\n"
            "        return worker\n"
        ))
        assert rules_of(lint_source(src, COLD)) == ["LOCKAWAIT"]

    def test_nested_sync_helper_in_async_not_flagged(self):
        # the asyncio.to_thread pattern: the helper runs OFF the loop
        src = _LOCK_CLASS.format(body=(
            "    async def f(self):\n"
            "        import asyncio\n"
            "        def helper():\n"
            "            self._tlock.acquire()\n"
            "            self._tlock.release()\n"
            "        await asyncio.to_thread(helper)\n"
        ))
        assert rules_of(lint_source(src, COLD)) == []

    def test_module_level_lock_tracked(self):
        src = (
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "async def f(coro):\n"
            "    with LOCK:\n"
            "        await coro\n"
        )
        assert rules_of(lint_source(src, COLD)) == ["LOCKAWAIT"]


# ---------------------------------------------------------------- RETRACE

class TestRetrace:
    def test_jit_in_loop_fires(self):
        src = (
            "import jax\n"
            "def f(xs):\n"
            "    for x in xs:\n"
            "        g = jax.jit(lambda a: a + x)\n"
        )
        hits = [f for f in lint_source(src, COLD) if not f.suppressed]
        assert any("inside a loop" in f.message for f in hits)

    def test_memoized_loop_construction_clean(self):
        # the runner-bucket pattern: one construction per cache key
        src = (
            "import jax\n"
            "def build(keys, cache):\n"
            "    for k in keys:\n"
            "        if k in cache:\n"
            "            continue\n"
            "        cache[k] = jax.jit(lambda a: a + 1)\n"
        )
        hits = [f for f in lint_source(src, COLD) if not f.suppressed]
        assert not any("inside a loop" in f.message for f in hits)

    def test_unmemoized_function_fires(self):
        src = (
            "import jax\n"
            "def per_step(x):\n"
            "    return jax.jit(lambda a: a + 1)(x)\n"
        )
        assert rules_of(lint_source(src, COLD)) == ["RETRACE"]

    def test_cache_membership_idiom_clean(self):
        src = (
            "import jax\n"
            "_cache = {}\n"
            "def get_fn(k):\n"
            "    if k in _cache:\n"
            "        return _cache[k]\n"
            "    fn = jax.jit(lambda a: a + 1)\n"
            "    _cache[k] = fn\n"
            "    return fn\n"
        )
        assert rules_of(lint_source(src, COLD)) == []

    def test_lru_cache_decorator_clean(self):
        src = (
            "import functools, jax\n"
            "@functools.lru_cache\n"
            "def get_fn(k):\n"
            "    return jax.jit(lambda a: a + k)\n"
        )
        assert rules_of(lint_source(src, COLD)) == []

    def test_lazy_init_idiom_clean(self):
        src = (
            "import jax\n"
            "class R:\n"
            "    def key(self):\n"
            "        if self._fold is None:\n"
            "            self._fold = jax.jit(jax.random.fold_in)\n"
            "        return self._fold\n"
        )
        assert rules_of(lint_source(src, COLD)) == []

    def test_module_level_jit_clean(self):
        src = "import jax\nf = jax.jit(lambda a: a + 1)\n"
        assert rules_of(lint_source(src, COLD)) == []

    def test_loop_variable_capture_fires(self):
        src = (
            "import jax\n"
            "def f(xs):\n"
            "    fns = {}\n"
            "    for scale in xs:\n"
            "        if scale in fns:\n"
            "            continue\n"
            "        def step(a):\n"
            "            return a * scale\n"
            "        fns[scale] = jax.jit(step)\n"
            "    return fns\n"
        )
        hits = [f for f in lint_source(src, COLD) if not f.suppressed]
        assert any("loop variable" in f.message for f in hits)

    def test_unhashable_static_arg_fires(self):
        src = (
            "import jax\n"
            "def g(shape, x):\n"
            "    if x in ():\n"
            "        pass\n"
            "    return jax.jit(lambda s, a: a, static_argnums=(0,))([1, 2], x)\n"
        )
        hits = [f for f in lint_source(src, COLD) if not f.suppressed]
        assert any("unhashable" in f.message for f in hits)

    def test_from_jax_import_jit_tracked(self):
        src = (
            "from jax import jit\n"
            "def per_step(x):\n"
            "    return jit(lambda a: a)(x)\n"
        )
        assert rules_of(lint_source(src, COLD)) == ["RETRACE"]


# ---------------------------------------------------------------- GUARDED

_GUARDED_CLASS = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
{body}
"""


class TestGuarded:
    def test_lockfree_read_of_guarded_field_fires(self):
        src = _GUARDED_CLASS.format(body=(
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n"
            "    def peek(self):\n"
            "        return self._n\n"
        ))
        hits = rules_of(lint_source(src, COLD), "GUARDED")
        assert hits == ["GUARDED"]

    def test_lockfree_write_fires(self):
        src = _GUARDED_CLASS.format(body=(
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._n = 1\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            self._n = 2\n"
            "    def c(self):\n"
            "        self._n = 3\n"
        ))
        findings = [f for f in lint_source(src, COLD)
                    if f.rule == "GUARDED" and not f.suppressed]
        assert len(findings) == 1 and "write to self._n" in findings[0].message

    def test_majority_not_met_stays_quiet(self):
        # half the writes are lock-free: no discipline to infer
        src = _GUARDED_CLASS.format(body=(
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._n = 1\n"
            "    def b(self):\n"
            "        self._n = 2\n"
        ))
        assert rules_of(lint_source(src, COLD), "GUARDED") == []

    def test_init_writes_do_not_count(self):
        # the only non-init write is locked; __init__'s unlocked one is
        # pre-publication and must not dilute the census
        src = _GUARDED_CLASS.format(body=(
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n"
        ))
        assert rules_of(lint_source(src, COLD), "GUARDED") == []

    def test_locked_context_helper_clean(self):
        # the *_locked convention: every call site holds the lock
        src = _GUARDED_CLASS.format(body=(
            "    def _advance_locked(self):\n"
            "        self._n += 1\n"
            "    def tick(self):\n"
            "        with self._lock:\n"
            "            self._advance_locked()\n"
            "    def tock(self):\n"
            "        with self._lock:\n"
            "            self._advance_locked()\n"
            "            self._n = 5\n"
        ))
        assert rules_of(lint_source(src, COLD), "GUARDED") == []

    def test_helper_with_unlocked_caller_fires(self):
        src = _GUARDED_CLASS.format(body=(
            "    def _advance(self):\n"
            "        self._n += 1\n"
            "    def tick(self):\n"
            "        with self._lock:\n"
            "            self._advance()\n"
            "            self._n = 2\n"
            "    def tock(self):\n"
            "        with self._lock:\n"
            "            self._n = 3\n"
            "    def free(self):\n"
            "        self._advance()\n"
        ))
        findings = rules_of(lint_source(src, COLD), "GUARDED")
        assert findings == ["GUARDED"]  # the write inside _advance

    def test_condition_alias_counts_as_lock(self):
        src = (
            "import threading\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "        self._wakeup = threading.Condition(self._lock)\n"
            "        self._q = 0\n"
            "    def submit(self):\n"
            "        with self._wakeup:\n"
            "            self._q = 1\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            self._q = 2\n"
        )
        assert rules_of(lint_source(src, COLD), "GUARDED") == []

    def test_container_mutation_is_a_write(self):
        src = _GUARDED_CLASS.format(body=(
            "    def put(self, x):\n"
            "        with self._lock:\n"
            "            self._n = [x]\n"
            "    def put2(self, x):\n"
            "        with self._lock:\n"
            "            self._n.append(x)\n"
            "    def leak(self, x):\n"
            "        self._n.append(x)\n"
        ))
        findings = [f for f in lint_source(src, COLD)
                    if f.rule == "GUARDED" and not f.suppressed]
        assert len(findings) == 1 and "write to self._n" in findings[0].message

    def test_cross_thread_reachability_tagged(self):
        src = (
            "import threading\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._p = 0.0\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            self._p = 1.0\n"
            "    def go(self):\n"
            "        threading.Thread(target=self._watch).start()\n"
            "    def _watch(self):\n"
            "        return self._p\n"
        )
        findings = [f for f in lint_source(src, COLD)
                    if f.rule == "GUARDED" and not f.suppressed]
        assert len(findings) == 1
        assert "[cross-thread" in findings[0].message

    def test_thread_entry_is_not_a_locked_context(self):
        """A private method that is BOTH a Thread target and called in-class
        under the lock must not be inferred lock-held — the thread invokes
        it with nothing held (the watchdog-reads-progress-stamps race)."""
        src = (
            "import threading\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._p = 0\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            self._p = 1\n"
            "    def go(self):\n"
            "        threading.Thread(target=self._watch).start()\n"
            "    def tick(self):\n"
            "        with self._lock:\n"
            "            self._watch()\n"
            "    def _watch(self):\n"
            "        return self._p\n"
        )
        findings = [f for f in lint_source(src, COLD)
                    if f.rule == "GUARDED" and not f.suppressed]
        assert len(findings) == 1
        assert "[cross-thread" in findings[0].message

    def test_guarded_by_annotation_forces(self):
        src = (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.flips = 0  # smglint: guarded-by(_lock)\n"
            "    def flip(self):\n"
            "        self.flips += 1\n"
        )
        findings = [f for f in lint_source(src, COLD)
                    if f.rule == "GUARDED" and not f.suppressed]
        assert len(findings) == 1
        assert "guarded-by annotation" in findings[0].message

    def test_suppression(self):
        src = _GUARDED_CLASS.format(body=(
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n"
            "    def peek(self):\n"
            "        return self._n  # smglint: disable=GUARDED atomic int read\n"
        ))
        findings = [f for f in lint_source(src, COLD) if f.rule == "GUARDED"]
        assert findings and all(f.suppressed for f in findings)

    def test_class_without_thread_lock_skipped(self):
        src = (
            "class S:\n"
            "    def __init__(self):\n"
            "        self._n = 0\n"
            "    def a(self):\n"
            "        self._n += 1\n"
        )
        assert rules_of(lint_source(src, COLD), "GUARDED") == []


# -------------------------------------------------------------- FRAMEFOLD

FF = "smg_tpu/engine/scheduler.py"


def _ff(findings):
    return [f for f in findings if f.rule == "FRAMEFOLD" and not f.suppressed]


class TestFrameFold:
    def test_discarded_launch_fires(self):
        src = (
            "class S:\n"
            "    def step(self):\n"
            "        self._launch_frame([])\n"
        )
        hits = _ff(lint_source(src, FF))
        assert len(hits) == 1 and "result discarded" in hits[0].message

    def test_real_decode_batch_shape_clean(self):
        src = (
            "class S:\n"
            "    def _decode_batch(self, active, outputs):\n"
            "        frame = self._launch_frame(active)\n"
            "        if frame is not None:\n"
            "            try:\n"
            "                fetch, used = self._consume_frame(frame, outputs)\n"
            "            except Exception:\n"
            "                self.inflight = frame\n"
            "                raise\n"
            "            if used < frame.horizon:\n"
            "                self._rewind_unused_folds(frame, used)\n"
        )
        assert _ff(lint_source(src, FF)) == []

    def test_consume_without_try_fires(self):
        src = (
            "class S:\n"
            "    def step(self, active, outputs):\n"
            "        frame = self._launch_frame(active)\n"
            "        fetch, used = self._consume_frame(frame, outputs)\n"
            "        if used < frame.horizon:\n"
            "            self._rewind_unused_folds(frame, used)\n"
        )
        hits = _ff(lint_source(src, FF))
        assert len(hits) == 1 and "exception-edge" in hits[0].message

    def test_handler_that_rewinds_counts_as_protection(self):
        src = (
            "class S:\n"
            "    def step(self, active, outputs):\n"
            "        frame = self._launch_frame(active)\n"
            "        try:\n"
            "            self._consume_frame(frame, outputs)\n"
            "        except Exception:\n"
            "            self._discard_frame(frame)\n"
            "            raise\n"
            "        self._rewind_unused_folds(frame, 0)\n"
        )
        assert _ff(lint_source(src, FF)) == []

    def test_never_resolved_frame_fires(self):
        src = (
            "class S:\n"
            "    def step(self):\n"
            "        frame = self._launch_frame([])\n"
            "        if frame is None:\n"
            "            return\n"
        )
        hits = _ff(lint_source(src, FF))
        assert len(hits) == 1 and "never" in hits[0].message

    def test_early_return_between_launch_and_resolution_fires(self):
        src = (
            "class S:\n"
            "    def step(self, cond, outputs):\n"
            "        frame = self._launch_frame([])\n"
            "        if cond:\n"
            "            return None\n"
            "        try:\n"
            "            self._consume_frame(frame, outputs)\n"
            "        except Exception:\n"
            "            self.inflight = frame\n"
            "            raise\n"
            "        self._rewind_unused_folds(frame, 0)\n"
        )
        hits = _ff(lint_source(src, FF))
        assert len(hits) == 1 and "exit path leaks" in hits[0].message

    def test_none_guard_return_clean(self):
        # `if frame is None: return` — the launcher bailed pre-fold
        src = (
            "class S:\n"
            "    def step(self, outputs):\n"
            "        frame = self._launch_spec_frame([], {}, False)\n"
            "        if frame is None:\n"
            "            return\n"
            "        self.inflight = frame\n"
        )
        assert _ff(lint_source(src, FF)) == []

    def test_missing_tail_rewind_fires(self):
        src = (
            "class S:\n"
            "    def step(self, outputs):\n"
            "        frame = self._launch_frame([])\n"
            "        try:\n"
            "            self._consume_frame(frame, outputs)\n"
            "        except Exception:\n"
            "            self.inflight = frame\n"
            "            raise\n"
        )
        hits = _ff(lint_source(src, FF))
        assert len(hits) == 1 and "_rewind_unused_folds" in hits[0].message

    def test_discarded_and_dead_fold_marks_fire(self):
        src = (
            "class R:\n"
            "    def go(self, n):\n"
            "        self._consume_folds(n)\n"
            "        mark = self._consume_folds(n)\n"
            "        return 1\n"
        )
        hits = _ff(lint_source(src, FF))
        assert len(hits) == 2
        assert any("mark discarded" in f.message for f in hits)
        assert any("never used" in f.message for f in hits)

    def test_mark_used_in_call_clean(self):
        src = (
            "class R:\n"
            "    def go(self, n):\n"
            "        mark = self._consume_folds(n)\n"
            "        return self._run(mark)\n"
        )
        assert _ff(lint_source(src, FF)) == []

    def test_suppression(self):
        src = (
            "class S:\n"
            "    def step(self):\n"
            "        self._launch_frame([])  # smglint: disable=FRAMEFOLD bench-only fire-and-forget\n"
        )
        findings = [f for f in lint_source(src, FF) if f.rule == "FRAMEFOLD"]
        assert findings and all(f.suppressed for f in findings)


# -------------------------------------------------------------- LOCKORDER

_ORDER_SRC = """
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
{body}
"""


class TestLockOrder:
    def test_both_orders_fire_once(self):
        src = _ORDER_SRC.format(body=(
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        ))
        hits = rules_of(lint_source(src, COLD), "LOCKORDER")
        assert hits == ["LOCKORDER"]

    def test_consistent_order_clean(self):
        src = _ORDER_SRC.format(body=(
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "    def two(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
        ))
        assert rules_of(lint_source(src, COLD), "LOCKORDER") == []

    def test_multi_item_with_counts_as_nesting(self):
        src = _ORDER_SRC.format(body=(
            "    def one(self):\n"
            "        with self._a, self._b:\n"
            "            pass\n"
            "    def two(self):\n"
            "        with self._b, self._a:\n"
            "            pass\n"
        ))
        assert rules_of(lint_source(src, COLD), "LOCKORDER") == ["LOCKORDER"]

    def test_condition_aliases_to_its_lock(self):
        """`with self._lock: with self._wakeup:` and the reverse are the
        SAME lock (Condition(self._lock) acquires it) — reentrant nesting,
        not a two-lock inversion (the engine's _lock/_wakeup pattern)."""
        src = (
            "import threading\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "        self._wakeup = threading.Condition(self._lock)\n"
            "    def one(self):\n"
            "        with self._lock:\n"
            "            with self._wakeup:\n"
            "                pass\n"
            "    def two(self):\n"
            "        with self._wakeup:\n"
            "            with self._lock:\n"
            "                pass\n"
        )
        assert rules_of(lint_source(src, COLD), "LOCKORDER") == []

    def test_cross_module_inversion(self, tmp_path):
        (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "m1.py").write_text(_ORDER_SRC.format(body=(
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
        )))
        (pkg / "m2.py").write_text(_ORDER_SRC.format(body=(
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        )))
        findings = [f for f in lint_paths([pkg]) if f.rule == "LOCKORDER"]
        assert len(findings) == 1
        # anchored in one module, message points at the other site
        assert findings[0].path == "pkg/m1.py"
        assert "pkg/m2.py" in findings[0].message

    def test_runs_do_not_leak_pairs(self, tmp_path):
        """Fresh rule instances per run: module A's pairs must not combine
        with a LATER run's module B into a phantom inversion."""
        one = _ORDER_SRC.format(body=(
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
        ))
        two = _ORDER_SRC.format(body=(
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        ))
        assert rules_of(lint_source(one, COLD), "LOCKORDER") == []
        assert rules_of(lint_source(two, COLD), "LOCKORDER") == []

    def test_suppression_at_anchor_site(self):
        src = _ORDER_SRC.format(body=(
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:  # smglint: disable=LOCKORDER documented order exception\n"
            "                pass\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        ))
        findings = [f for f in lint_source(src, COLD) if f.rule == "LOCKORDER"]
        assert findings and all(f.suppressed for f in findings)


# -------------------------------------------------------------- TRACEPURE

class TestTracePure:
    def test_attribute_store_in_jitted_body_fires(self):
        src = (
            "import jax\n"
            "class R:\n"
            "    def go(self, x):\n"
            "        @jax.jit\n"
            "        def step(a):\n"
            "            self.h = a\n"
            "            return a + 1\n"
            "        return step(x)\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE")

    def test_outer_container_append_fires(self):
        src = (
            "import jax\n"
            "trace = []\n"
            "@jax.jit\n"
            "def step(a):\n"
            "    trace.append(a)\n"
            "    return a * 2\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE")

    def test_host_clock_in_scan_body_fires(self):
        # call-site closure form: the body reaches lax.scan as a bare name
        src = (
            "import time\n"
            "from jax import lax\n"
            "def run(xs):\n"
            "    def body(c, x):\n"
            "        t = time.time()\n"
            "        return c + x, t\n"
            "    return lax.scan(body, 0.0, xs)\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE")

    def test_branch_on_traced_value_fires(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def step(a):\n"
            "    if a > 0:\n"
            "        return a\n"
            "    return -a\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE")

    def test_print_in_traced_body_fires(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def step(a):\n"
            "    print(a)\n"
            "    return a\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE")

    def test_shape_unpack_branch_is_static(self):
        # the ops/pallas FP class: names derived from .shape/.dtype/len()
        # are host-static metadata, branching on them is legal
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def step(k_cache):\n"
            "    L, P, ps, KD = k_cache.shape\n"
            "    if KD % 128 != 0:\n"
            "        raise ValueError(KD)\n"
            "    return k_cache\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE") == []

    def test_static_argnames_param_branch_clean(self):
        src = (
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, static_argnames=('interpret',))\n"
            "def step(a, interpret):\n"
            "    if interpret:\n"
            "        return a\n"
            "    return a * 2\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE") == []

    def test_is_none_staging_clean(self):
        src = (
            "import jax\n"
            "def build(mask):\n"
            "    @jax.jit\n"
            "    def step(a):\n"
            "        if mask is None:\n"
            "            return a\n"
            "        return a * mask\n"
            "    return step\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE") == []

    def test_consumed_functional_update_clean(self):
        # optax idiom: tx.update returns fresh values — not a mutation
        src = (
            "import jax\n"
            "def build(tx):\n"
            "    @jax.jit\n"
            "    def step(grads, opt_state):\n"
            "        updates, opt_state = tx.update(grads, opt_state)\n"
            "        return updates, opt_state\n"
            "    return step\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE") == []

    def test_jax_random_is_not_stdlib_random(self):
        src = (
            "from jax import random\n"
            "import jax\n"
            "@jax.jit\n"
            "def step(key, a):\n"
            "    return a + random.normal(key, a.shape)\n"
        )
        assert rules_of(lint_source(src, COLD), "TRACEPURE") == []

    def test_suppressed(self):
        src = (
            "import jax\n"
            "class R:\n"
            "    def go(self, x):\n"
            "        @jax.jit\n"
            "        def step(a):\n"
            "            self.h = a  # smglint: disable=TRACEPURE debug-only capture\n"
            "            return a\n"
            "        return step(x)\n"
        )
        findings = [f for f in lint_source(src, COLD) if f.rule == "TRACEPURE"]
        assert findings and all(f.suppressed for f in findings)


# ----------------------------------------------------------------- DONATE

class TestDonate:
    def test_read_after_donate_fires(self):
        src = (
            "import jax\n"
            "class R:\n"
            "    def go(self, x):\n"
            "        def step(k, x):\n"
            "            return k * x\n"
            "        fn = jax.jit(step, donate_argnums=(0,))\n"
            "        out = fn(self.k, x)\n"
            "        return out + self.k.sum()\n"
        )
        hits = rules_of(lint_source(src, COLD), "DONATE")
        assert hits == ["DONATE"]

    def test_reassignment_kill_clean(self):
        # the runner's sanctioned pattern: rebind from the program outputs
        src = (
            "import jax\n"
            "class R:\n"
            "    def go(self, x):\n"
            "        def step(k, x):\n"
            "            return x, k\n"
            "        fn = jax.jit(step, donate_argnums=(0,))\n"
            "        out, self.k = fn(self.k, x)\n"
            "        return out\n"
        )
        assert rules_of(lint_source(src, COLD), "DONATE") == []

    def test_retained_donated_buffer_fires(self):
        # never reassigned: the object keeps a deleted array around
        src = (
            "import jax\n"
            "class R:\n"
            "    def go(self, x):\n"
            "        def step(k, x):\n"
            "            return x, k\n"
            "        fn = jax.jit(step, donate_argnums=(0,))\n"
            "        out, _ = fn(self.k, x)\n"
            "        return out\n"
        )
        assert rules_of(lint_source(src, COLD), "DONATE") == ["DONATE"]

    def test_nonexistent_donate_position_fires(self):
        src = (
            "import jax\n"
            "def build():\n"
            "    def step(a, b):\n"
            "        return a + b\n"
            "    return jax.jit(step, donate_argnums=(5,))\n"
        )
        hits = rules_of(lint_source(src, COLD), "DONATE")
        assert hits == ["DONATE"]

    def test_donating_through_parameter_fires(self):
        # DecodeState case: the caller does not own the buffer it donates
        src = (
            "import jax\n"
            "class R:\n"
            "    def go(self, state, x):\n"
            "        def step(k, x):\n"
            "            return x, k\n"
            "        fn = jax.jit(step, donate_argnums=(0,))\n"
            "        out, _ = fn(state.k_cache, x)\n"
            "        return out\n"
        )
        assert rules_of(lint_source(src, COLD), "DONATE") == ["DONATE"]

    def test_factory_dispatch_with_args_list_resolved(self):
        # the runner shape: jit built in a factory method, dispatched from
        # another method through `fn = self._fn(...)` and `args = [...]`
        src = (
            "import jax\n"
            "class R:\n"
            "    def _fn(self):\n"
            "        def step(k, x):\n"
            "            return x, k\n"
            "        return jax.jit(step, donate_argnums=(0,))\n"
            "    def go(self, x):\n"
            "        fn = self._fn()\n"
            "        args = [self.k, x]\n"
            "        out = fn(*args)\n"
            "        return out[0] + self.k.mean()\n"
        )
        assert rules_of(lint_source(src, COLD), "DONATE") == ["DONATE"]

    def test_policy_variable_argnums_resolved(self):
        # `donate = (0,) if policy else ()` — union of literal bindings
        src = (
            "import jax\n"
            "class R:\n"
            "    def go(self, x, policy):\n"
            "        def step(k, x):\n"
            "            return x, k\n"
            "        donate = (0,) if policy else ()\n"
            "        fn = jax.jit(step, donate_argnums=donate)\n"
            "        out, _ = fn(self.k, x)\n"
            "        return out + self.k.sum()\n"
        )
        assert rules_of(lint_source(src, COLD), "DONATE") == ["DONATE"]

    def test_suppressed(self):
        src = (
            "import jax\n"
            "class R:\n"
            "    def go(self, x):\n"
            "        def step(k, x):\n"
            "            return k * x\n"
            "        fn = jax.jit(step, donate_argnums=(0,))\n"
            "        out = fn(self.k, x)\n"
            "        return out + self.k.sum()  # smglint: disable=DONATE re-uploaded next call\n"
        )
        findings = [f for f in lint_source(src, COLD) if f.rule == "DONATE"]
        assert findings and all(f.suppressed for f in findings)


# -------------------------------------------------------------- SHARDDISC

# SHARDDISC is scoped to LintConfig.shard_paths (sharded-decode modules);
# parallel/sharding.py is in the shard set but NOT the hot set, so fixtures
# exercise SHARDDISC without HOTSYNC interference
SHARD = "smg_tpu/parallel/sharding.py"


class TestShardDisc:
    def test_bare_device_put_fires(self):
        src = "import jax\ndef up(x):\n    return jax.device_put(x)\n"
        assert rules_of(lint_source(src, SHARD)) == ["SHARDDISC"]

    def test_device_put_with_sharding_clean(self):
        src = (
            "import jax\n"
            "def up(x, sharding):\n"
            "    return jax.device_put(x, sharding)\n"
        )
        assert rules_of(lint_source(src, SHARD)) == []

    def test_inline_kv_carry_without_hint_fires(self):
        src = (
            "import jax.numpy as jnp\n"
            "from jax import lax\n"
            "def run(n, L, B, N, KD):\n"
            "    def cond(c):\n"
            "        return c[0] < n\n"
            "    def body(c):\n"
            "        return (c[0] + 1, c[1])\n"
            "    return lax.while_loop(\n"
            "        cond, body, (0, jnp.zeros((L, B, N, KD))))\n"
        )
        assert rules_of(lint_source(src, SHARD)) == ["SHARDDISC"]

    def test_unhinted_named_kv_carry_fires(self):
        src = (
            "import jax.numpy as jnp\n"
            "from jax import lax\n"
            "def run(n, L, B, KD):\n"
            "    hk0 = jnp.zeros((L, B, KD))\n"
            "    def cond(c):\n"
            "        return c[0] < n\n"
            "    def body(c):\n"
            "        return (c[0] + 1, c[1])\n"
            "    return lax.while_loop(cond, body, (0, hk0))\n"
        )
        assert rules_of(lint_source(src, SHARD)) == ["SHARDDISC"]

    def test_shard_hint_rewrap_clean(self):
        # the megastep's sanctioned pattern: last assignment is the hint
        src = (
            "import jax.numpy as jnp\n"
            "from jax import lax\n"
            "from smg_tpu.parallel.sharding import shard_hint\n"
            "def run(n, L, B, KD, mesh, rules):\n"
            "    hk0 = jnp.zeros((L, B, KD))\n"
            "    hk0 = shard_hint(hk0, ('layers', None, 'kv_lanes'), mesh, rules)\n"
            "    def cond(c):\n"
            "        return c[0] < n\n"
            "    def body(c):\n"
            "        return (c[0] + 1, c[1])\n"
            "    return lax.while_loop(cond, body, (0, hk0))\n"
        )
        assert rules_of(lint_source(src, SHARD)) == []

    def test_small_bookkeeping_carry_exempt(self):
        # [B]-sized counters are cheap to replicate — rank < 3 stays quiet
        src = (
            "import jax.numpy as jnp\n"
            "from jax import lax\n"
            "def run(n, B):\n"
            "    def cond(c):\n"
            "        return c[0] < n\n"
            "    def body(c):\n"
            "        return (c[0] + 1, c[1])\n"
            "    return lax.while_loop(cond, body, (0, jnp.zeros((B,))))\n"
        )
        assert rules_of(lint_source(src, SHARD)) == []

    def test_out_of_scope_module_exempt(self):
        src = "import jax\ndef up(x):\n    return jax.device_put(x)\n"
        assert rules_of(lint_source(src, COLD)) == []

    def test_suppressed(self):
        src = (
            "import jax\n"
            "def up(x):\n"
            "    return jax.device_put(x)  # smglint: disable=SHARDDISC single-device fallback\n"
        )
        findings = [f for f in lint_source(src, SHARD) if f.rule == "SHARDDISC"]
        assert findings and all(f.suppressed for f in findings)


# ------------------------------------------------- engine mechanics

class TestEngineMechanics:
    def test_file_level_suppression(self):
        src = (
            "# smglint: disable-file=HOTSYNC grandfathered module\n"
            "def f(x):\n"
            "    return x.item()\n"
        )
        findings = lint_source(src, HOT)
        assert findings and all(f.suppressed for f in findings)

    def test_multiline_statement_trailing_suppression(self):
        # the finding anchors at the first line; the comment sits on the last
        src = (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(\n"
            "        x\n"
            "    )  # smglint: disable=HOTSYNC Host-only normalization\n"
        )
        findings = lint_source(src, HOT)
        assert findings and all(f.suppressed for f in findings)

    def test_disable_next_skips_blank_lines(self):
        src = (
            "# smglint: disable-next=HOTSYNC reason\n"
            "\n"
            "def f(x):\n"
            "    return 1\n"
        )
        # no finding on the def line, but the mechanics must not misanchor:
        # the same form over an actual finding
        src2 = (
            "def f(x):\n"
            "    # smglint: disable-next=HOTSYNC reason\n"
            "    # (explanatory comment in between)\n"
            "    return x.item()\n"
        )
        assert lint_source(src, HOT) == []
        findings = lint_source(src2, HOT)
        assert findings and all(f.suppressed for f in findings)

    def test_docstring_directive_text_never_registers(self):
        # documentation QUOTING the syntax must not grant live immunity
        src = (
            '"""Docs for the tool.\n'
            "\n"
            "    x = arr.item()  # smglint: disable=HOTSYNC why\n"
            "    # smglint: disable-file=ASYNCBLOCK\n"
            '"""\n'
            "import time\n"
            "async def f(x):\n"
            "    time.sleep(1)\n"
            "    return x.item()\n"
        )
        findings = lint_source(src, HOT)
        assert sorted(rules_of(findings)) == ["ASYNCBLOCK", "HOTSYNC"]
        assert not any(f.suppressed for f in findings)

    def test_star_suppression(self):
        src = "def f(x):\n    return x.item()  # smglint: disable=* legacy\n"
        assert all(f.suppressed for f in lint_source(src, HOT))

    def test_uppercase_justification_not_swallowed(self):
        # "KV export helper" must read as justification, not as rule tokens
        src = (
            "def f(x):\n"
            "    return x.item()  # smglint: disable=HOTSYNC KV Export helper\n"
        )
        findings = lint_source(src, HOT)
        assert findings and all(f.suppressed for f in findings)

    def test_multi_rule_suppression_with_justification(self):
        src = (
            "import time\n"
            "async def f(x):\n"
            "    time.sleep(1)  # smglint: disable=ASYNCBLOCK,HOTSYNC Why Not\n"
        )
        assert all(f.suppressed for f in lint_source(src, HOT))

    def test_syntax_error_is_a_finding(self):
        findings = lint_source("def f(:\n", HOT)
        assert [f.rule for f in findings] == ["PARSE"]

    def test_non_utf8_module_lints_not_crashes(self, tmp_path):
        # PEP 263 coding cookie: legal Python, not UTF-8 on disk
        good = tmp_path / "latin.py"
        good.write_bytes(b"# -*- coding: latin-1 -*-\nNAME = '\xe9'\n")
        assert lint_paths([good]) == []
        # genuinely undecodable bytes degrade to a PARSE finding
        bad = tmp_path / "bad.py"
        bad.write_bytes(b"\x00\xff\xfe garbage \xff")
        findings = lint_paths([bad])
        assert [f.rule for f in findings] == ["PARSE"]

    def test_rule_subset(self):
        src = "import time\nasync def f(x):\n    time.sleep(1)\n    return x.item()\n"
        cfg = LintConfig(rules=("ASYNCBLOCK",))
        assert rules_of(lint_source(src, HOT, cfg)) == ["ASYNCBLOCK"]

    def test_unknown_rule_raises(self):
        with pytest.raises(KeyError):
            lint_source("x = 1\n", HOT, LintConfig(rules=("NOPE",)))

    def test_baseline_roundtrip(self, tmp_path):
        src = "def f(x):\n    return x.item()\n"
        findings = lint_source(src, HOT)
        bl = tmp_path / "baseline.json"
        write_baseline(findings, bl)
        marked = apply_baseline(lint_source(src, HOT), load_baseline(bl))
        assert all(f.baselined for f in marked)

    def test_baseline_budget_catches_new_duplicates(self, tmp_path):
        one = "def f(x):\n    return x.item()\n"
        two = "def f(x):\n    return x.item()\n\ndef g(x):\n    return x.item()\n"
        bl = tmp_path / "baseline.json"
        write_baseline(lint_source(one, HOT), bl)
        marked = apply_baseline(lint_source(two, HOT), load_baseline(bl))
        # identical source lines share a key: one grandfathered, one NEW
        assert sum(f.baselined for f in marked) == 1
        assert sum(not f.baselined for f in marked) == 1

    def test_baseline_is_line_number_independent(self, tmp_path):
        src = "def f(x):\n    return x.item()\n"
        moved = "# a new comment shifting lines\n\n" + src
        bl = tmp_path / "baseline.json"
        write_baseline(lint_source(src, HOT), bl)
        marked = apply_baseline(lint_source(moved, HOT), load_baseline(bl))
        assert all(f.baselined for f in marked)


# ----------------------------------------------------- CLI / self-lint

class TestCli:
    def run_cli(self, *args):
        return subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts" / "smglint.py"), *args],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )

    def test_self_lint_zero_unbaselined(self):
        """THE acceptance gate: the whole package lints clean."""
        r = self.run_cli("smg_tpu/")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 new finding(s)" in r.stdout

    def test_cli_fails_on_finding(self, tmp_path):
        bad = tmp_path / "smg_tpu" / "engine"
        bad.mkdir(parents=True)
        mod = bad / "scheduler.py"
        mod.write_text("def f(x):\n    return x.item()\n")
        r = self.run_cli(str(mod), "--no-baseline")
        assert r.returncode == 1
        assert "HOTSYNC" in r.stdout

    def test_cli_json_format(self, tmp_path):
        mod = tmp_path / "smg_tpu" / "engine" / "scheduler.py"
        mod.parent.mkdir(parents=True)
        mod.write_text("def f(x):\n    return x.item()\n")
        r = self.run_cli(str(mod), "--no-baseline", "--format", "json")
        data = json.loads(r.stdout)
        assert data and data[0]["rule"] == "HOTSYNC"

    def test_write_baseline_then_clean(self, tmp_path):
        mod = tmp_path / "smg_tpu" / "engine" / "scheduler.py"
        mod.parent.mkdir(parents=True)
        mod.write_text("def f(x):\n    return x.item()\n")
        bl = tmp_path / "bl.json"
        r = self.run_cli(str(mod), "--write-baseline", "--baseline", str(bl))
        assert r.returncode == 0
        r = self.run_cli(str(mod), "--baseline", str(bl))
        assert r.returncode == 0, r.stdout

    def test_missing_path_is_usage_error(self):
        """A vanished/misspelled path must fail loudly (exit 2), not pass
        green with nothing linted — CI-gate integrity."""
        r = self.run_cli("does_not_exist_anywhere/")
        assert r.returncode == 2
        assert "does not exist" in r.stderr

    def test_write_baseline_default_lands_at_repo_root(self, tmp_path):
        """--write-baseline without --baseline must write where the next
        run's default lookup reads: beside pyproject.toml."""
        (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
        mod = tmp_path / "smg_tpu" / "engine" / "scheduler.py"
        mod.parent.mkdir(parents=True)
        mod.write_text("def f(x):\n    return x.item()\n")
        r = self.run_cli(str(mod), "--write-baseline")
        assert r.returncode == 0
        assert (tmp_path / "smglint_baseline.json").exists()
        r = self.run_cli(str(mod))  # default lookup now finds it
        assert r.returncode == 0, r.stdout

    def test_narrowed_write_baseline_preserves_other_scope(self, tmp_path):
        """--write-baseline with --rules (or a sub-path) must not erase the
        grandfathered debt of rules/paths outside the run's scope."""
        (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
        pkg = tmp_path / "smg_tpu" / "engine"
        pkg.mkdir(parents=True)
        mod = pkg / "scheduler.py"
        mod.write_text(
            "import time\n"
            "def f(x):\n"
            "    return x.item()\n"
            "async def g():\n"
            "    time.sleep(1)\n"
        )
        bl = tmp_path / "bl.json"
        # full-scope baseline: one HOTSYNC + one ASYNCBLOCK entry
        r = self.run_cli(str(tmp_path / "smg_tpu"), "--baseline", str(bl),
                         "--write-baseline")
        assert r.returncode == 0
        full = json.loads(bl.read_text())["findings"]
        assert {k.split(":")[0] for k in full} == {"HOTSYNC", "ASYNCBLOCK"}
        # narrowed regeneration must keep the ASYNCBLOCK entry
        r = self.run_cli(str(tmp_path / "smg_tpu"), "--baseline", str(bl),
                         "--rules", "HOTSYNC", "--write-baseline")
        assert r.returncode == 0
        merged = json.loads(bl.read_text())["findings"]
        assert merged == full
        # and the full run still passes under the merged baseline
        r = self.run_cli(str(tmp_path / "smg_tpu"), "--baseline", str(bl))
        assert r.returncode == 0, r.stdout

    def test_repo_paths_lint_everywhere(self):
        """Every repo-relative path the ISSUE names is inside the lint scope
        actually exercised by the self-lint invocation."""
        findings = lint_paths([REPO_ROOT / "smg_tpu"])
        paths = {f.path for f in findings}  # suppressed findings still listed
        # hot modules carry intentional, justified suppressions
        assert any(p.startswith("smg_tpu/engine") for p in paths)

    def test_new_rule_families_in_default_set(self):
        """GUARDED/FRAMEFOLD/LOCKORDER and the JAX-discipline trio
        TRACEPURE/DONATE/SHARDDISC ship enabled — the self-lint gate above
        runs them; this pins the registry so a refactor can't drop one
        silently."""
        from smg_tpu.analysis.rules import ALL_RULES

        assert {"GUARDED", "FRAMEFOLD", "LOCKORDER",
                "TRACEPURE", "DONATE", "SHARDDISC"} <= set(ALL_RULES)

    def test_changed_lints_only_changed_files(self, tmp_path):
        """--changed REF: same exit codes and baseline path as a full run,
        but only the files touched since REF are linted."""
        (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
        pkg = tmp_path / "smg_tpu" / "engine"
        pkg.mkdir(parents=True)
        clean = pkg / "runner.py"
        clean.write_text("def f(x):\n    return x\n")
        dirty = pkg / "scheduler.py"
        dirty.write_text("def f(x):\n    return x\n")

        def git(*args):
            subprocess.run(["git", "-C", str(tmp_path), *args],
                           capture_output=True, text=True, check=True)

        git("init", "-q")
        git("-c", "user.email=t@t", "-c", "user.name=t", "add", ".")
        git("-c", "user.email=t@t", "-c", "user.name=t",
            "commit", "-qm", "seed")
        # touch ONLY scheduler.py with a HOTSYNC finding; runner.py keeps a
        # (hypothetical) clean state and must not even be read
        dirty.write_text("def f(x):\n    return x.item()\n")
        r = subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts" / "smglint.py"),
             "--changed", "--no-baseline"],
            capture_output=True, text=True, cwd=tmp_path,
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "scheduler.py" in r.stdout
        assert "runner.py" not in r.stdout
        # suppression works identically on the fast path
        dirty.write_text(
            "def f(x):\n"
            "    return x.item()  # smglint: disable=HOTSYNC why\n"
        )
        r = subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts" / "smglint.py"),
             "--changed", "--no-baseline"],
            capture_output=True, text=True, cwd=tmp_path,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        # vs an explicit REF with nothing changed: clean no-op, exit 0
        git("-c", "user.email=t@t", "-c", "user.name=t", "add", ".")
        git("-c", "user.email=t@t", "-c", "user.name=t",
            "commit", "-qm", "wip")
        r = subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts" / "smglint.py"),
             "--changed", "HEAD", "--no-baseline"],
            capture_output=True, text=True, cwd=tmp_path,
        )
        assert r.returncode == 0
        assert "no Python files changed" in r.stdout

    def test_changed_rejects_write_baseline(self):
        r = self.run_cli("--changed", "--write-baseline")
        assert r.returncode == 2
        assert "full-scope" in r.stderr

    def test_paths_required_without_changed(self):
        r = self.run_cli()
        assert r.returncode == 2
        assert "paths required" in r.stderr

    def test_sarif_format_round_trip(self, tmp_path):
        """--format sarif: valid SARIF 2.1.0 whose results agree with the
        json format finding-for-finding."""
        mod = tmp_path / "smg_tpu" / "engine" / "scheduler.py"
        mod.parent.mkdir(parents=True)
        mod.write_text(
            "import threading\n"
            "import jax\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._n = 1\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            self._n = 2\n"
            "    def c(self):\n"
            "        return self._n\n"
            "    def d(self, x):\n"
            "        def step(k, x):\n"
            "            return k * x\n"
            "        fn = jax.jit(step, donate_argnums=(0,))\n"
            "        out = fn(self.k, x)\n"
            "        return out + self.k.sum()\n"
            "def f(x):\n"
            "    return x.item()\n"
            "def up(x):\n"
            "    return jax.device_put(x)\n"
            "@jax.jit\n"
            "def traced(a):\n"
            "    import time\n"
            "    return a * time.time()\n"
        )
        rj = self.run_cli(str(mod), "--no-baseline", "--format", "json")
        rs = self.run_cli(str(mod), "--no-baseline", "--format", "sarif")
        assert rj.returncode == 1 and rs.returncode == 1
        plain = json.loads(rj.stdout)
        sarif = json.loads(rs.stdout)
        assert sarif["version"] == "2.1.0"
        run = sarif["runs"][0]
        assert run["tool"]["driver"]["name"] == "smglint"
        results = run["results"]
        assert len(results) == len(plain) >= 5
        by_rule = {r["ruleId"] for r in results}
        assert {"GUARDED", "HOTSYNC", "DONATE", "SHARDDISC",
                "TRACEPURE"} <= by_rule
        # locations round-trip: same (path, line, 1-based col) per finding
        got = {
            (r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"],
             r["locations"][0]["physicalLocation"]["region"]["startLine"],
             r["locations"][0]["physicalLocation"]["region"]["startColumn"])
            for r in results
        }
        want = {(f["path"], f["line"], f["col"] + 1) for f in plain}
        assert got == want
        # every emitted ruleId resolves into the driver rule table
        rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
        for r in results:
            assert rule_ids[r["ruleIndex"]] == r["ruleId"]


# ----------------------------------------------- runtime guards (probes)

def _tiny_engine(overlap=True):
    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.models.config import tiny_test_config

    return Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=128, max_prefill_tokens=32,
            prefill_token_buckets=(32,), decode_batch_buckets=(4,),
            decode_horizon=2, overlap_schedule=overlap,
        ),
        dtype="float32", seed=0,
    ))


class TestRuntimeGuards:
    """The two probes the static rules pair with: steady-state decode does
    not transfer implicitly and does not compile.  These are the runtime
    teeth behind HOTSYNC and RETRACE."""

    @pytest.mark.parametrize("overlap", [True, False])
    def test_steady_state_decode_is_guard_clean(self, overlap):
        from smg_tpu.analysis.runtime_guards import steady_state_guard
        from smg_tpu.protocols.sampling import SamplingParams

        eng = _tiny_engine(overlap)
        done = {}
        prompts = [[(7 * i + j) % 90 + 5 for j in range(16)] for i in range(2)]
        for i, p in enumerate(prompts):
            eng.submit(
                p,
                SamplingParams(temperature=0.0, max_new_tokens=48,
                               ignore_eos=True),
                rid=f"r{i}",
                on_output=lambda o, i=i: done.setdefault(i, []).append(o),
            )
        for _ in range(6):  # warmup: prefill + prime the pipeline + compiles
            eng.step()
        # any implicit transfer raises inside jax; >0 compiles raise after
        with steady_state_guard() as cc:
            for _ in range(8):
                eng.step()
        assert cc.count == 0
        while eng.scheduler.has_work():
            eng.step()
        lens = {i: sum(len(o.new_token_ids) for o in v) for i, v in done.items()}
        assert lens == {0: 48, 1: 48}

    def test_compile_counter_sees_compiles(self):
        import jax
        import jax.numpy as jnp

        from smg_tpu.analysis.runtime_guards import CompileCounter

        with CompileCounter() as cc:
            # a fresh lambda identity guarantees an uncached lowering
            jax.jit(lambda a: a * 3 + 1)(jnp.arange(7))  # smglint: disable=RETRACE one-shot jit is the fixture under test
        assert cc.count >= 1

    def test_transfer_guard_catches_implicit_transfer(self):
        import jax.numpy as jnp
        import numpy as np

        from smg_tpu.analysis.runtime_guards import no_implicit_transfers

        dev = jnp.arange(8)
        with pytest.raises(Exception, match="[Tt]ransfer"):
            with no_implicit_transfers():
                dev + np.int32(3)  # numpy scalar leaks into device math

    def test_recompile_budget_enforced(self):
        import jax
        import jax.numpy as jnp

        from smg_tpu.analysis.runtime_guards import steady_state_guard

        with pytest.raises(RuntimeError, match="compiled"):
            with steady_state_guard(max_compiles=0):
                jax.jit(lambda a: a - 11)(jnp.arange(3))  # smglint: disable=RETRACE deliberate compile to trip the guard


# ------------------------------------------ compiled-program audit (runtime)

def _sharded_engine(cpu_devices, tp):
    from smg_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from smg_tpu.engine.engine import Engine
    from smg_tpu.models.config import tiny_test_config
    from smg_tpu.tokenizer import MockTokenizer

    cfg = EngineConfig(
        model=tiny_test_config(),
        parallel=ParallelConfig(tp=tp) if tp > 1 else ParallelConfig(),
        cache=CacheConfig(page_size=16, num_pages=96, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=256, max_prefill_tokens=64,
            prefill_token_buckets=(32, 64), decode_batch_buckets=(4,),
            overlap_schedule=False,
        ),
        dtype="float32",
    )
    return Engine(cfg, tokenizer=MockTokenizer(), devices=cpu_devices[:tp])


class TestProgramAudit:
    """The runtime half of the JAX-discipline tentpole: after warmup, every
    cached compiled program is auditable from its lowered/compiled
    representation — committed shardings, donation aliasing
    (``input_output_alias``), and recompile provenance."""

    def _drive(self, eng, n=12):
        from smg_tpu.protocols.sampling import SamplingParams

        return eng.generate(
            prompt_ids=list(range(5, 30)),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=n,
                                    ignore_eos=True),
        )

    @pytest.mark.parametrize("tp", [1, 8])
    def test_steady_state_audit_clean(self, cpu_devices, tp):
        """THE acceptance probe: tp=1 and tp=8 engines audit clean — zero
        uncommitted/mismatched steady-state inputs, every intended donation
        verified-aliased in the compiled HLO, zero recompiles while armed."""
        from smg_tpu.analysis.runtime_guards import program_audit

        eng = _sharded_engine(cpu_devices, tp)
        self._drive(eng)                    # warmup: compiles + first traffic
        eng.runner._programs.arm()
        self._drive(eng)                    # armed steady-state traffic
        report = program_audit(eng)
        assert report["uncommitted_inputs"] == 0, report
        assert report["sharding_mismatches"] == 0, report
        assert report["donation_unverified"] == 0, report
        assert report["recompiles"] == 0, report
        assert report["clean"], report
        # donation was actually exercised, not vacuously absent: at least
        # one audited program declared donation and verified its aliases
        donated = [p for p in report["programs"] if p.get("donation")]
        assert donated, report
        for p in donated:
            assert p["donation"]["verified"]
            assert p["donation"]["aliased"] == p["donation"]["intended"] > 0
        # and the cheap snapshot rides loads() for operators
        snap = eng.loads()["programs"]
        assert snap["armed"] and snap["recompiles"] == 0
        assert len(snap["programs"]) == len(report["programs"])

    def test_uncommitted_input_is_caught(self, cpu_devices):
        """A deliberately-uncommitted input on a mesh program must be
        flagged: it pays an implicit reshard at every launch."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        from smg_tpu.analysis.runtime_guards import ProgramAuditor

        mesh = Mesh(np.array(cpu_devices[:8]).reshape(8), ("tp",))
        repl = NamedSharding(mesh, PartitionSpec())
        auditor = ProgramAuditor()
        fn = jax.jit(lambda a, b: a + b, in_shardings=(repl, repl))  # smglint: disable=RETRACE one-shot toy program for the auditor
        launch = auditor.wrap(("toy",), fn, in_shardings=(repl, repl))
        committed = jax.device_put(jnp.ones((4, 4)), repl)
        uncommitted = jnp.ones((4, 4))      # default-device, no commitment
        auditor.arm()
        launch(committed, uncommitted)
        report = auditor.audit()
        assert report["uncommitted_inputs"] == 1
        assert not report["clean"]
        bad = report["programs"][0]["bad_inputs"]
        assert bad[0]["why"] == "uncommitted"

    def test_recompile_provenance_names_the_argument(self):
        """An induced shape change between armed launches must be recorded
        with WHICH argument changed and how — not just a compile count."""
        import jax
        import jax.numpy as jnp

        from smg_tpu.analysis.runtime_guards import ProgramAuditor

        auditor = ProgramAuditor()
        launch = auditor.wrap(("shape",), jax.jit(lambda x: x * 2))  # smglint: disable=RETRACE the retrace IS the fixture
        auditor.arm()
        launch(jnp.ones((4,)))
        launch(jnp.ones((8,)))              # induced retrace
        prog = auditor.audit()["programs"][0]
        assert prog["recompiles"] >= 1
        change = prog["provenance"][0]["changed"][0]
        assert change["field"] == "shape"
        assert change["before"] == (4,) and change["after"] == (8,)

    def test_unarmed_wrapper_captures_nothing(self):
        import jax
        import jax.numpy as jnp

        from smg_tpu.analysis.runtime_guards import ProgramAuditor

        auditor = ProgramAuditor()
        launch = auditor.wrap(("idle",), jax.jit(lambda x: x + 1))  # smglint: disable=RETRACE one-shot toy program for the auditor
        launch(jnp.ones((3,)))              # unarmed: plain passthrough
        report = auditor.audit()
        assert report["programs"][0]["audited"] is False
        assert report["clean"]              # nothing captured, nothing wrong

    def test_invalidate_compiled_drops_audit_records(self, cpu_devices):
        eng = _sharded_engine(cpu_devices, 1)
        self._drive(eng, n=4)
        assert eng.runner._programs.snapshot()["programs"]
        eng.runner.invalidate_compiled()
        assert eng.runner._programs.snapshot()["programs"] == []


# ------------------------------------------- lock-order sentinel (runtime)

class TestLockOrderSentinel:
    """The LOCKORDER rule's runtime twin: lockdep-style dynamic order
    tracking on the locks the engine/recorder/gateway create through
    ``make_lock``."""

    def test_unarmed_make_lock_is_plain(self, monkeypatch):
        import threading

        import smg_tpu.analysis.runtime_guards as rg
        from smg_tpu.analysis.runtime_guards import make_lock

        # neutralize any ambient arming (SMG_LOCK_SENTINEL-armed CI runs)
        monkeypatch.delenv(rg.SENTINEL_ENV, raising=False)
        monkeypatch.setattr(rg, "_ambient_sentinel", None)
        assert isinstance(make_lock("x"), type(threading.Lock()))
        # reentrant flavor: an RLock (acquirable twice on one thread)
        r = make_lock("y", reentrant=True)
        with r:
            with r:
                pass

    def test_clean_order_passes(self):
        from smg_tpu.analysis.runtime_guards import lock_order_sentinel, make_lock

        with lock_order_sentinel() as s:
            a, b = make_lock("a"), make_lock("b")
            for _ in range(3):
                with a:
                    with b:
                        pass
        assert s.inversions == []

    def test_deliberate_inversion_fails_loudly_with_both_stacks(self):
        """THE repro the ISSUE asks for: an ABBA inversion must fail the
        block with BOTH acquisition stacks in the error."""
        from smg_tpu.analysis.runtime_guards import (
            LockOrderError,
            lock_order_sentinel,
            make_lock,
        )

        with pytest.raises(LockOrderError) as ei:
            with lock_order_sentinel():
                a, b = make_lock("a"), make_lock("b")
                with a:
                    with b:
                        pass
                with b:
                    with a:
                        pass
        msg = str(ei.value)
        assert "a -> b" in msg and "b -> a" in msg
        # both stacks present, each pointing into THIS test
        assert msg.count("stack that") >= 2
        assert msg.count("test_deliberate_inversion") >= 2

    def test_raise_on_inversion_pinpoints_and_unwinds(self):
        from smg_tpu.analysis.runtime_guards import (
            LockOrderError,
            lock_order_sentinel,
            make_lock,
        )

        with pytest.raises(LockOrderError):
            with lock_order_sentinel(raise_on_inversion=True) as s:
                a, b = make_lock("a"), make_lock("b")
                with a:
                    with b:
                        pass
                with b:
                    with a:  # raises HERE, at the closing acquisition
                        pass
        # the offending lock was rolled back: nothing left held
        assert not a.locked() and not b.locked()
        assert len(s.inversions) == 1

    def test_cross_thread_inversion_detected(self):
        import threading

        from smg_tpu.analysis.runtime_guards import (
            LockOrderError,
            lock_order_sentinel,
            make_lock,
        )

        with pytest.raises(LockOrderError):
            with lock_order_sentinel():
                a, b = make_lock("a"), make_lock("b")

                def t1():
                    with a:
                        with b:
                            pass

                th = threading.Thread(target=t1)
                th.start()
                th.join()
                with b:
                    with a:
                        pass

    def test_reentrant_lock_not_self_edged(self):
        from smg_tpu.analysis.runtime_guards import lock_order_sentinel, make_lock

        with lock_order_sentinel() as s:
            r = make_lock("engine", reentrant=True)
            with r:
                with r:  # depth 2: no self-edge, no phantom inversion
                    pass
        assert s.inversions == []

    def test_condition_on_sentinel_rlock_works(self):
        import threading
        import time

        from smg_tpu.analysis.runtime_guards import lock_order_sentinel, make_lock

        with lock_order_sentinel() as s:
            lock = make_lock("engine", reentrant=True)
            cv = threading.Condition(lock)
            got = []

            def waiter():
                with cv:
                    cv.wait(timeout=5)
                    got.append(1)

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cv:
                cv.notify_all()
            t.join(timeout=5)
            assert got == [1]
        assert s.inversions == []

    def test_engine_workload_under_sentinel_is_inversion_free(self):
        """The acceptance probe: a real engine boot + decode + watchdog-era
        locks (engine RLock, flight recorder, metrics) under the sentinel
        records ZERO order inversions."""
        from smg_tpu.analysis.runtime_guards import lock_order_sentinel
        from smg_tpu.protocols.sampling import SamplingParams

        with lock_order_sentinel() as s:
            eng = _tiny_engine(overlap=True)
            done = []
            eng.submit(
                [7, 9, 11, 13] * 4,
                SamplingParams(temperature=0.0, max_new_tokens=16,
                               ignore_eos=True),
                rid="sentinel-probe",
                on_output=lambda o: done.append(o),
            )
            while eng.scheduler.has_work():
                eng.step()
            eng.stop(drain=True, timeout=5.0)
            assert sum(len(o.new_token_ids) for o in done) == 16
        assert s.inversions == []
