"""Responses API + conversations + MCP tool loop + storage backends
(reference: e2e responses/messages suites + data_connector tests)."""

import asyncio
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.gateway.server import AppContext, build_app
from smg_tpu.gateway.worker_client import InProcWorkerClient
from smg_tpu.gateway.workers import Worker
from smg_tpu.mcp import LocalToolServer
from smg_tpu.models.config import tiny_test_config
from smg_tpu.storage import ConversationItem, MemoryStorage, SqliteStorage, StoredResponse
from smg_tpu.tokenizer import MockTokenizer


# ---- storage backends ----

@pytest.mark.parametrize("backend", ["memory", "sqlite"])
def test_storage_backend_roundtrip(backend):
    async def go():
        s = MemoryStorage() if backend == "memory" else SqliteStorage(":memory:")
        conv = await s.create_conversation({"topic": "x"})
        assert (await s.get_conversation(conv.id)).metadata == {"topic": "x"}
        await s.update_conversation(conv.id, {"y": 1})
        assert (await s.get_conversation(conv.id)).metadata == {"topic": "x", "y": 1}

        items = [
            ConversationItem(type="message", role="user", content={"content": "hi"}),
            ConversationItem(type="message", role="assistant", content={"content": "yo"}),
        ]
        await s.add_items(conv.id, items)
        got = await s.list_items(conv.id)
        assert [i.role for i in got] == ["user", "assistant"]
        assert await s.delete_item(conv.id, got[0].id)
        assert len(await s.list_items(conv.id)) == 1

        r1 = await s.store_response(StoredResponse(model="m", output=[{"type": "message"}]))
        r2 = await s.store_response(
            StoredResponse(model="m", previous_response_id=r1.id)
        )
        chain = await s.response_chain(r2.id)
        assert [r.id for r in chain] == [r1.id, r2.id]
        assert await s.delete_response(r1.id)
        assert await s.get_conversation("nope") is None
        assert await s.delete_conversation(conv.id)
        assert await s.get_conversation(conv.id) is None

    asyncio.run(go())


# ---- mcp ----

def test_local_mcp_server_and_registry():
    async def go():
        from smg_tpu.mcp import McpRegistry

        srv = LocalToolServer("test")
        srv.register("add", lambda a, b: {"sum": a + b}, "adds numbers",
                     {"type": "object", "properties": {"a": {}, "b": {}}})
        reg = McpRegistry()
        reg.add(srv)
        tools = await reg.list_tools()
        assert tools[0].name == "add"
        result = await reg.call_tool("add", {"a": 2, "b": 3})
        assert '"sum": 5' in result
        from smg_tpu.mcp import ToolNotFound

        with pytest.raises(ToolNotFound):
            await reg.call_tool("nope", {})

    asyncio.run(go())


# ---- gateway fixture ----

@pytest.fixture(scope="module")
def agw():
    loop = asyncio.new_event_loop()
    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("tiny-test", MockTokenizer(), default=True)
    engine = Engine(
        EngineConfig(
            model=tiny_test_config(),
            cache=CacheConfig(page_size=16, num_pages=256, auto_size=False, dtype="float32"),
            scheduler=SchedulerConfig(
                max_batch_size=8, max_seq_len=256, max_prefill_tokens=64,
                prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(4, 8),
            ),
            dtype="float32",
            model_id="tiny-test",
        )
    )

    async def _setup():
        ctx.registry.add(
            Worker(worker_id="w0", client=InProcWorkerClient(engine), model_id="tiny-test")
        )
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=120)

    tc = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.client, h.ctx = run, tc, ctx
    yield h
    run(tc.close())
    loop.call_soon_threadsafe(loop.stop)
    engine.stop()


def test_conversation_crud(agw):
    async def go():
        r = await agw.client.post("/v1/conversations", json={"metadata": {"t": "demo"}})
        conv = await r.json()
        r2 = await agw.client.get(f"/v1/conversations/{conv['id']}")
        r3 = await agw.client.post(
            f"/v1/conversations/{conv['id']}/items",
            json={"items": [{"type": "message", "role": "user", "content": "w1 w2"}]},
        )
        r4 = await agw.client.get(f"/v1/conversations/{conv['id']}/items")
        r5 = await agw.client.delete(f"/v1/conversations/{conv['id']}")
        r6 = await agw.client.get(f"/v1/conversations/{conv['id']}")
        return conv, (await r2.json()), (await r4.json()), r5.status, r6.status

    conv, got, items, del_status, gone_status = agw.run(go())
    assert got["id"] == conv["id"]
    assert got["metadata"] == {"t": "demo"}
    assert len(items["data"]) == 1
    assert del_status == 200 and gone_status == 404


def test_responses_create_and_retrieve(agw):
    async def go():
        r = await agw.client.post(
            "/v1/responses",
            json={"model": "tiny-test", "input": "w5 w6 w7",
                  "max_output_tokens": 6, "temperature": 0},
        )
        resp = await r.json()
        r2 = await agw.client.get(f"/v1/responses/{resp['id']}")
        r3 = await agw.client.delete(f"/v1/responses/{resp['id']}")
        r4 = await agw.client.get(f"/v1/responses/{resp['id']}")
        return r.status, resp, (await r2.json()), r3.status, r4.status

    status, resp, got, del_status, gone = agw.run(go())
    assert status == 200
    assert resp["object"] == "response"
    assert resp["status"] == "completed"
    msg = next(i for i in resp["output"] if i["type"] == "message")
    assert msg["content"][0]["text"].startswith("w")
    assert got["id"] == resp["id"]
    assert del_status == 200 and gone == 404


def test_responses_chaining(agw):
    async def go():
        r1 = await agw.client.post(
            "/v1/responses",
            json={"model": "tiny-test", "input": "w1 w2", "max_output_tokens": 4,
                  "temperature": 0},
        )
        first = await r1.json()
        r2 = await agw.client.post(
            "/v1/responses",
            json={"model": "tiny-test", "input": "w3 w4", "max_output_tokens": 4,
                  "temperature": 0, "previous_response_id": first["id"]},
        )
        return first, await r2.json()

    first, second = agw.run(go())
    assert second["previous_response_id"] == first["id"]
    assert second["status"] == "completed"


def test_responses_mcp_tool_loop(agw):
    """Wire a fake worker that emits a tool call on the first turn and plain
    text on the second, plus a local MCP tool — the loop must execute the tool
    server-side and produce both items."""
    ctx = agw.ctx

    calls_made = []
    srv = LocalToolServer("calc")
    srv.register("add", lambda a, b: (calls_made.append((a, b)), {"sum": a + b})[1],
                 "adds", {"type": "object"})
    ctx.mcp.add(srv)

    from smg_tpu.gateway.worker_client import WorkerClient, WorkerStreamChunk

    class ScriptedClient(WorkerClient):
        """Protocol-accurate fake worker (reference: crates/mock_worker)."""

        def __init__(self, scripts):
            self.scripts = scripts
            self.turn = 0

        async def generate(self, req):
            text = self.scripts[min(self.turn, len(self.scripts) - 1)]
            self.turn += 1
            ids = self.tokenizer.encode(text)
            yield WorkerStreamChunk(
                rid=req.rid, token_ids=ids, finished=True, finish_reason="stop",
                prompt_tokens=len(req.input_ids), output_tokens=len(ids),
            )

        async def abort(self, rid):
            return True

        async def health(self):
            return True

        async def get_loads(self):
            return {"num_waiting": 0, "num_running": 0, "free_pages": 1,
                    "cached_pages": 0, "total_pages": 1}

        async def flush_cache(self):
            return True

    # scripted output needs arbitrary text to round-trip through incremental
    # detokenization: assign each encoded chunk of text its own token id
    class TextTokenizer(MockTokenizer):
        def __init__(self):
            super().__init__()
            self.pieces = {}
            self._next = 10

        def decode(self, ids, skip_special_tokens=True):
            return "".join(self.pieces.get(int(t), "") for t in ids)

        def encode(self, text, add_special_tokens=False):
            ids = []
            for i in range(0, len(text), 4):
                tid = self._next
                self._next += 1
                self.pieces[tid] = text[i : i + 4]
                ids.append(tid)
            return ids

    tok = TextTokenizer()
    scripted = ScriptedClient(
        ['{"name": "add", "arguments": {"a": 2, "b": 5}}', "the sum is seven"]
    )
    scripted.tokenizer = tok

    async def go():
        ctx.tokenizers.register("scripted", tok)
        ctx.registry.add(Worker(worker_id="scripted-w", client=scripted, model_id="scripted"))
        r = await agw.client.post(
            "/v1/responses",
            json={"model": "scripted", "input": "add two and five",
                  "temperature": 0, "max_output_tokens": 16},
        )
        body = await r.json()
        ctx.registry.remove("scripted-w")
        return r.status, body

    status, body = agw.run(go())
    assert status == 200, body
    types = [i["type"] for i in body["output"]]
    assert "function_call" in types
    assert "function_call_output" in types
    fc_out = next(i for i in body["output"] if i["type"] == "function_call_output")
    assert '"sum": 7' in fc_out["output"]
    assert calls_made == [(2, 5)]
    assert "message" in types  # final answer after tool result


def test_responses_stream_events(agw):
    async def go():
        resp = await agw.client.post(
            "/v1/responses",
            json={"model": "tiny-test", "input": "w8", "max_output_tokens": 3,
                  "temperature": 0, "stream": True},
        )
        return await resp.text()

    raw = agw.run(go())
    events = [l[7:] for l in raw.splitlines() if l.startswith("event: ")]
    assert events[0] == "response.created"
    assert "response.output_text.delta" in events
    assert events[-1] == "response.completed"


def test_anthropic_tool_blocks_translate():
    """tool_use / tool_result blocks must survive translation to chat
    messages (review finding: the standard Anthropic tool loop)."""
    from smg_tpu.protocols.anthropic import AnthropicMessagesRequest

    req = AnthropicMessagesRequest.model_validate(
        {
            "model": "m",
            "max_tokens": 10,
            "messages": [
                {"role": "user", "content": "what is 2+5?"},
                {
                    "role": "assistant",
                    "content": [
                        {"type": "text", "text": "let me compute"},
                        {"type": "tool_use", "id": "tu_1", "name": "add",
                         "input": {"a": 2, "b": 5}},
                    ],
                },
                {
                    "role": "user",
                    "content": [
                        {"type": "tool_result", "tool_use_id": "tu_1", "content": "7"},
                    ],
                },
            ],
        }
    )
    msgs = req.to_chat_messages()
    assert msgs[0]["role"] == "user"
    assert msgs[1]["role"] == "assistant"
    assert msgs[1]["tool_calls"][0]["function"]["name"] == "add"
    assert msgs[2]["role"] == "tool"
    assert msgs[2]["content"] == "7"
    assert msgs[2]["tool_call_id"] == "tu_1"
