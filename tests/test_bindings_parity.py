"""Go bindings / native C ABI parity pins (VERDICT r4 missing #5).

No Go toolchain ships in this environment, so these tests pin the
contracts the Go package depends on: the C header matches the symbols
libsmg_native actually exports, and the Go client targets routes the
gateway actually serves."""

import re
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_c_header_matches_native_exports():
    header = (ROOT / "csrc" / "smg_native.h").read_text()
    cpp = (ROOT / "csrc" / "radix_index.cpp").read_text()
    exported = set(re.findall(r"^\s*(?:void\*?|size_t)\s+(rt_\w+)\(",
                              cpp, re.M))
    declared = set(re.findall(r"(rt_\w+)\(", header))
    assert exported, "no exports found in radix_index.cpp"
    assert exported == declared, (exported, declared)


def test_go_client_targets_served_routes():
    go = (ROOT / "bindings" / "golang" / "client.go").read_text()
    server = (ROOT / "smg_tpu" / "gateway" / "server.py").read_text()
    for route in re.findall(r'"(/v1/[a-z/]+|/generate|/health|/workers)"', go):
        assert route in server, f"Go client targets unserved route {route}"


def test_go_native_uses_header_symbols():
    radix_go = (ROOT / "bindings" / "golang" / "native" / "radix.go").read_text()
    header = (ROOT / "csrc" / "smg_native.h").read_text()
    for sym in re.findall(r"C\.(rt_\w+)\(", radix_go):
        assert sym in header, f"cgo calls undeclared symbol {sym}"
    assert '#include "smg_native.h"' in radix_go


def test_native_lib_symbols_when_built():
    """When the auto-built .so exists, its dynamic symbols must cover the
    header (the Go LDFLAGS link against it)."""
    import subprocess

    so = ROOT / "csrc" / "libsmg_native.so"
    if not so.exists():
        import pytest

        pytest.skip("libsmg_native.so not built")
    out = subprocess.run(["nm", "-D", str(so)], capture_output=True, text=True)
    header = (ROOT / "csrc" / "smg_native.h").read_text()
    for sym in re.findall(r"(rt_\w+)\(", header):
        assert sym in out.stdout, f"{sym} missing from libsmg_native.so"
