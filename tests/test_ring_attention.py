"""Ring attention vs dense causal attention on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smg_tpu.engine.config import ParallelConfig
from smg_tpu.parallel.mesh import build_mesh
from smg_tpu.parallel.ring_attention import ring_attention


def dense_causal(q, k, v, scale):
    B, T, H, D = q.shape
    K = k.shape[2]
    G = H // K
    qf = q.astype(jnp.float32).reshape(B, T, K, G, D)
    scores = jnp.einsum("btkgd,bskd->btkgs", qf, k.astype(jnp.float32)) * scale
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask[None, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("btkgs,bskd->btkgd", probs, v.astype(jnp.float32))
    return out.reshape(B, T, H, D).astype(q.dtype)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense(cpu_devices, sp):
    mesh = build_mesh(ParallelConfig(sp=sp), devices=cpu_devices[:sp])
    B, T, H, K, D = 2, 32, 8, 2, 16
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, T, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, T, K, D), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, T, K, D), jnp.float32)
    scale = 1.0 / np.sqrt(D)

    ref = dense_causal(q, k, v, scale)
    out = ring_attention(q, k, v, mesh, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_with_dp_and_sp(cpu_devices):
    """Ring attention composes with a dp-sharded batch."""
    mesh = build_mesh(ParallelConfig(dp=2, sp=4), devices=cpu_devices[:8])
    B, T, H, K, D = 4, 16, 4, 4, 8
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (B, T, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, T, K, D), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, T, K, D), jnp.float32)
    scale = 1.0 / np.sqrt(D)
    ref = dense_causal(q, k, v, scale)
    out = ring_attention(q, k, v, mesh, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sp_serving_prefill_matches_single(cpu_devices):
    """Sequence-parallel SERVING prefill (ring attention on the cold first
    chunk of a long prompt) is token-exact vs single device (VERDICT r1 weak
    #7: ring was train-only)."""
    from smg_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from smg_tpu.engine.engine import Engine
    from smg_tpu.models.config import tiny_test_config
    from smg_tpu.protocols.sampling import SamplingParams
    from smg_tpu.tokenizer import MockTokenizer

    def eng(parallel, devs):
        cfg = EngineConfig(
            model=tiny_test_config(),
            parallel=parallel,
            cache=CacheConfig(page_size=16, num_pages=96, auto_size=False,
                              dtype="float32"),
            scheduler=SchedulerConfig(
                max_batch_size=4, max_seq_len=256, max_prefill_tokens=64,
                prefill_token_buckets=(32, 64), decode_batch_buckets=(4,),
            ),
            dtype="float32",
        )
        return Engine(cfg, tokenizer=MockTokenizer(), devices=devs)

    sampling = SamplingParams(temperature=0.0, max_new_tokens=8, ignore_eos=True)
    # 100 tokens > max_prefill_tokens=64 -> solo chunked prefill; chunk 1 is
    # cold (ring path under sp), chunk 2 extends the cache (dense path)
    prompt = [(i * 7) % 90 + 5 for i in range(100)]
    single = eng(ParallelConfig(), cpu_devices[:1])
    ref = single.generate(prompt_ids=prompt, sampling=sampling)
    sp4 = eng(ParallelConfig(sp=4), cpu_devices[:4])
    runner = sp4.runner
    res = sp4.generate(prompt_ids=prompt, sampling=sampling)
    assert res.token_ids == ref.token_ids
    # the ring variant actually compiled (cold chunk T=64 % sp=4 == 0).
    # Prefill compile keys are ("prefill", T, mp, impl, use_pen, use_mask,
    # use_lora, use_ring, ...): match use_ring by position, not k[-1], so
    # appending new flags to the key doesn't break this assertion.
    assert any(k[0] == "prefill" and k[7] for k in runner._compiled), (
        "expected a use_ring=True prefill variant to be compiled"
    )
