"""Ring attention vs dense causal attention on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smg_tpu.engine.config import ParallelConfig
from smg_tpu.parallel.mesh import build_mesh
from smg_tpu.parallel.ring_attention import ring_attention


def dense_causal(q, k, v, scale):
    B, T, H, D = q.shape
    K = k.shape[2]
    G = H // K
    qf = q.astype(jnp.float32).reshape(B, T, K, G, D)
    scores = jnp.einsum("btkgd,bskd->btkgs", qf, k.astype(jnp.float32)) * scale
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask[None, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("btkgs,bskd->btkgd", probs, v.astype(jnp.float32))
    return out.reshape(B, T, H, D).astype(q.dtype)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense(cpu_devices, sp):
    mesh = build_mesh(ParallelConfig(sp=sp), devices=cpu_devices[:sp])
    B, T, H, K, D = 2, 32, 8, 2, 16
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, T, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, T, K, D), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, T, K, D), jnp.float32)
    scale = 1.0 / np.sqrt(D)

    ref = dense_causal(q, k, v, scale)
    out = ring_attention(q, k, v, mesh, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_with_dp_and_sp(cpu_devices):
    """Ring attention composes with a dp-sharded batch."""
    mesh = build_mesh(ParallelConfig(dp=2, sp=4), devices=cpu_devices[:8])
    B, T, H, K, D = 4, 16, 4, 4, 8
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (B, T, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, T, K, D), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, T, K, D), jnp.float32)
    scale = 1.0 / np.sqrt(D)
    ref = dense_causal(q, k, v, scale)
    out = ring_attention(q, k, v, mesh, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
