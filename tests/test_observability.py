"""Engine-deep observability (ISSUE 1): unified gateway+engine metric
registry, step-loop telemetry, engine-stage span parenting, and /metrics
exposition of engine series driven through a real (CPU-backed) engine.

Layout mirrors the layer split: unit tests for ``gateway/observability.py``
and ``engine/metrics.py``, tracing-stage tests for ``gateway/tracing.py``,
the docs-drift gate (``scripts/check_metric_docs.py``), then an e2e section
that drives requests through the full aiohttp app + in-proc engine (same
harness as test_gateway.py) and scrapes ``/metrics``."""

import asyncio
import importlib.util
import re
from pathlib import Path

import pytest
from aiohttp.test_utils import TestClient, TestServer
from prometheus_client import CollectorRegistry

from smg_tpu.engine.metrics import EngineMetrics, RollingStepStats
from smg_tpu.gateway.observability import Metrics, current_route

REPO_ROOT = Path(__file__).resolve().parent.parent


def metric_value(text: str, name: str, labels: dict | None = None) -> float | None:
    """Value of the first exposition sample matching ``name`` and (a superset
    of) ``labels``; None when no sample matches."""
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = re.match(r"([a-zA-Z_:][\w:]*)(?:\{(.*)\})?\s+(\S+)", line)
        if not m or m.group(1) != name:
            continue
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(2) or ""))
        if labels and any(got.get(k) != v for k, v in labels.items()):
            continue
        return float(m.group(3))
    return None


# ---- gateway Metrics: track_request status labels (satellite: 4xx/5xx
# responses returned without raising must not count as 200) ----


def test_track_request_defaults_to_200():
    m = Metrics()
    with m.track_request("/v1/chat/completions"):
        pass
    body = m.export().decode()
    assert metric_value(body, "smg_requests_total",
                        {"route": "/v1/chat/completions", "status": "200"}) == 1.0


def test_track_request_records_actual_status():
    m = Metrics()
    with m.track_request("/r") as track:
        track.status = "429"
    with m.track_request("/r") as track:
        track.status = 503  # ints are stringified
    body = m.export().decode()
    assert metric_value(body, "smg_requests_total", {"route": "/r", "status": "429"}) == 1.0
    assert metric_value(body, "smg_requests_total", {"route": "/r", "status": "503"}) == 1.0
    assert metric_value(body, "smg_requests_total", {"route": "/r", "status": "200"}) is None


def test_track_request_exception_counts_as_error():
    m = Metrics()
    with pytest.raises(RuntimeError):
        with m.track_request("/r"):
            raise RuntimeError("boom")
    body = m.export().decode()
    assert metric_value(body, "smg_requests_total", {"route": "/r", "status": "error"}) == 1.0
    assert metric_value(body, "smg_in_flight_requests") == 0.0


def test_track_request_sets_ambient_route():
    m = Metrics()
    assert current_route.get() == "unknown"
    with m.track_request("/v1/completions"):
        assert current_route.get() == "/v1/completions"
    assert current_route.get() == "unknown"


# ---- EngineMetrics: registration + unification ----


def test_engine_metrics_register_into_gateway_registry():
    gw = Metrics()
    em = EngineMetrics()
    em.register_into(gw.registry)
    body = gw.export().decode()
    # both layers in one scrape
    assert "smg_requests_total" in body
    assert "smg_engine_step_duration_seconds" in body
    assert "smg_engine_kv_page_utilization" in body
    # collectors stay live on the engine's own registry too
    em.kv_total_pages.set(64)
    assert metric_value(gw.export().decode(), "smg_engine_kv_total_pages") == 64.0


def test_engine_metrics_collision_rolls_back():
    gw = Metrics()
    em1, em2 = EngineMetrics(), EngineMetrics()
    em1.register_into(gw.registry)
    with pytest.raises(ValueError):
        em2.register_into(gw.registry)  # identical names collide
    # all-or-nothing: nothing from em2 leaked into the gateway registry,
    # and the original set still exports exactly once
    body = gw.export().decode()
    assert body.count("# TYPE smg_engine_kv_total_pages ") == 1
    # em2 remains fully usable on its own registry after the rollback
    em2.register_into(CollectorRegistry())


def test_engine_metrics_unregister_from():
    gw = Metrics()
    em = EngineMetrics()
    em.register_into(gw.registry)
    em.unregister_from(gw.registry)
    assert "smg_engine_" not in gw.export().decode()
    em.register_into(gw.registry)  # re-registrable after removal


def test_register_into_own_registry_is_noop():
    em = EngineMetrics()
    em.register_into(em.registry)  # must not raise on double-register


def test_worker_removal_releases_engine_metrics():
    """A removed in-proc worker's collectors leave the gateway registry, so
    a replacement engine's metric set can register without colliding."""
    from smg_tpu.gateway.server import AppContext

    class _Client:
        def __init__(self, em):
            self.engine_metrics = em

    class _Worker:
        def __init__(self, em, wid):
            self.client = _Client(em)
            self.worker_id = wid

    ctx = AppContext()
    em1, em2 = EngineMetrics(), EngineMetrics()
    ctx._maybe_adopt_worker_metrics("added", _Worker(em1, "w0"))
    assert "smg_engine_step_duration_seconds" in ctx.metrics.export().decode()
    ctx._maybe_adopt_worker_metrics("removed", _Worker(em1, "w0"))
    assert "smg_engine_" not in ctx.metrics.export().decode()
    # replacement engine registers cleanly
    assert ctx.adopt_engine_metrics(em2) is True
    assert "smg_engine_step_duration_seconds" in ctx.metrics.export().decode()


# ---- RollingStepStats ----


def test_rolling_stats_percentiles_and_rates():
    w = RollingStepStats(window_secs=10.0)
    for i in range(100):
        w.record(step_seconds=(i + 1) / 1000.0, prefill_tokens=10,
                 decode_tokens=5, now=100.0 + i * 0.01)
    snap = w.snapshot(now=101.0)
    assert snap["num_steps"] == 100
    assert snap["p50_step_seconds"] == pytest.approx(0.051, abs=0.002)
    assert snap["p95_step_seconds"] == pytest.approx(0.095, abs=0.002)
    assert snap["prefill_tokens_per_s"] > 0
    assert snap["tokens_per_s"] == pytest.approx(
        snap["prefill_tokens_per_s"] + snap["decode_tokens_per_s"])


def test_rolling_stats_window_prunes():
    w = RollingStepStats(window_secs=5.0)
    w.record(0.01, 1, 1, now=0.0)
    w.record(0.01, 1, 1, now=1.0)
    assert w.snapshot(now=1.0)["num_steps"] == 2
    snap = w.snapshot(now=100.0)  # both aged out
    assert snap["num_steps"] == 0
    assert snap["tokens_per_s"] == 0.0


def test_rolling_stats_bounded_samples():
    w = RollingStepStats(window_secs=1e9, max_samples=16)
    for i in range(100):
        w.record(0.01, 1, 1, now=float(i) * 1e-6)
    assert w.snapshot(now=1.0)["num_steps"] <= 16


# ---- EngineMetrics.observe_step: cumulative-counter delta tracking ----


def _observe(em, *, prefill_tokens=0, decode_tokens=0, running=0, cumulative=None):
    em.observe_step(
        step_s=0.01, prefill_s=0.005, decode_s=0.005,
        prefill_tokens=prefill_tokens, decode_tokens=decode_tokens,
        running=running, waiting=0, max_batch=8,
        free_pages=100, total_pages=128, cached_pages=4,
        cumulative=cumulative,
    )


def test_observe_step_converts_cumulatives_to_increments():
    em = EngineMetrics()
    _observe(em, prefill_tokens=32, decode_tokens=4, running=2,
             cumulative={"radix_hit_pages": 3, "cached_prompt_tokens": 48})
    _observe(em, decode_tokens=4, running=2,
             cumulative={"radix_hit_pages": 3, "cached_prompt_tokens": 48})
    # spec acceptance is per-lane-per-verify-block, not cumulative-delta
    em.observe_spec("ngram", 10, 6)
    em.observe_spec("ngram", 5, 3)
    from prometheus_client import generate_latest

    body = generate_latest(em.registry).decode()
    assert metric_value(body, "smg_engine_spec_drafted_tokens_total",
                        {"tier": "ngram"}) == 15.0
    assert metric_value(body, "smg_engine_spec_accepted_tokens_total",
                        {"tier": "ngram"}) == 9.0
    assert metric_value(body, "smg_engine_spec_accepted_length_count") == 2.0
    assert metric_value(body, "smg_engine_radix_hit_pages_total") == 3.0
    assert metric_value(body, "smg_engine_cached_prompt_tokens_total") == 48.0
    assert metric_value(body, "smg_engine_prefill_tokens_total") == 32.0
    assert metric_value(body, "smg_engine_decode_tokens_total") == 8.0
    assert metric_value(body, "smg_engine_step_duration_seconds_count",
                        {"phase": "step"}) == 2.0
    # prefill phase only observed on steps that actually prefilled
    assert metric_value(body, "smg_engine_step_duration_seconds_count",
                        {"phase": "prefill"}) == 1.0
    assert metric_value(body, "smg_engine_batch_occupancy") == 0.25
    assert metric_value(body, "smg_engine_kv_page_utilization") == pytest.approx(28 / 128)
    assert em.window.snapshot()["num_steps"] == 2


def test_observe_step_cumulative_reset_is_safe():
    em = EngineMetrics()
    from prometheus_client import generate_latest

    _observe(em, cumulative={"preemptions": 5})
    _observe(em, cumulative={"preemptions": 2})  # restart: smaller than last
    body = generate_latest(em.registry).decode()
    # no underflow; new baseline counts from the reset value
    assert metric_value(body, "smg_engine_preemptions_total") == 7.0


def test_on_finish_reason_labels():
    from prometheus_client import generate_latest

    em = EngineMetrics()
    em.on_finish("stop")
    em.on_finish("length")
    em.on_finish("")
    body = generate_latest(em.registry).decode()
    assert metric_value(body, "smg_engine_requests_finished_total", {"reason": "stop"}) == 1.0
    assert metric_value(body, "smg_engine_requests_finished_total", {"reason": "unknown"}) == 1.0


# ---- device memory gauges ----


class _FakeDev:
    platform, id = "tpu", 0

    def memory_stats(self):
        return {"bytes_in_use": 123, "bytes_limit": 1024}


class _NoStatsDev:
    platform, id = "cpu", 0

    def memory_stats(self):
        raise NotImplementedError


def test_sample_devices_reads_stats_and_guards_cpu():
    from prometheus_client import generate_latest

    em = EngineMetrics()
    assert em.sample_devices([_NoStatsDev()]) == 0
    assert em.sample_devices([_FakeDev(), _NoStatsDev()]) == 1
    body = generate_latest(em.registry).decode()
    assert metric_value(body, "smg_engine_hbm_bytes_in_use", {"device": "tpu:0"}) == 123.0
    assert metric_value(body, "smg_engine_hbm_bytes_limit", {"device": "tpu:0"}) == 1024.0


def test_sample_devices_skips_real_cpu_devices():
    import jax

    em = EngineMetrics()
    em.sample_devices(jax.devices("cpu"))  # must not raise; gauges stay empty or 0
    # whatever CPU reports, the call is guarded — no exception is the contract


def test_maybe_sample_devices_cadence():
    em = EngineMetrics(device_sample_interval_secs=10.0)
    assert em.maybe_sample_devices([_FakeDev()], now=100.0) is True
    assert em.maybe_sample_devices([_FakeDev()], now=105.0) is False
    assert em.maybe_sample_devices([_FakeDev()], now=110.1) is True


# ---- engine-stage spans (gateway/tracing.py) ----


def test_stage_spans_parent_under_ambient_request_span():
    from smg_tpu.gateway.tracing import (
        SPAN_KIND_INTERNAL,
        OtelTracer,
        current_span,
        current_tracer,
        end_stage,
        stage,
        start_stage,
    )

    tracer = OtelTracer("http://collector.invalid:4318")
    parent = tracer.start_span("POST /v1/chat/completions")
    t_tok = current_tracer.set(tracer)
    s_tok = current_span.set(parent)
    try:
        span = start_stage("engine.prefill", worker_id="w0")
        assert span is not None
        assert span.trace_id == parent.trace_id
        assert span.parent_span_id == parent.span_id
        assert span.kind == SPAN_KIND_INTERNAL
        assert span.attributes["worker_id"] == "w0"
        end_stage(span, cached_tokens=16)
        assert span.end_ns >= span.start_ns
        assert span in tracer._buffer  # recorded for export
        with pytest.raises(ValueError):
            with stage("engine.decode"):
                raise ValueError("boom")
        errored = tracer._buffer[-1]
        assert errored.name == "engine.decode"
        assert errored.status_code == 2  # error
    finally:
        current_span.reset(s_tok)
        current_tracer.reset(t_tok)


def test_stage_spans_are_none_without_ambient_tracer():
    from smg_tpu.gateway.tracing import end_stage, stage, start_stage

    assert start_stage("engine.prefill") is None
    end_stage(None)  # no-op
    with stage("engine.decode") as span:
        assert span is None


def test_parse_traceparent_validates_hex():
    from smg_tpu.gateway.tracing import parse_traceparent

    good = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    assert parse_traceparent(good) == ("ab" * 16, "cd" * 8)
    # uppercase is case-normalized, per W3C
    assert parse_traceparent(good.upper()) == ("ab" * 16, "cd" * 8)
    # correct lengths, garbage content — must NOT propagate
    assert parse_traceparent("00-" + "zz" * 16 + "-" + "cd" * 8 + "-01") is None
    assert parse_traceparent("00-" + "ab" * 16 + "-" + "zz" * 8 + "-01") is None
    assert parse_traceparent("0x-" + "ab" * 16 + "-" + "cd" * 8 + "-01") is None
    assert parse_traceparent("00-" + "ab" * 16 + "-" + "cd" * 8 + "-0g") is None
    # forbidden version
    assert parse_traceparent("ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01") is None


# ---- docs drift gate (scripts/check_metric_docs.py) ----


def _load_drift_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metric_docs", REPO_ROOT / "scripts" / "check_metric_docs.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metric_docs_in_sync():
    mod = _load_drift_checker()
    assert mod.check() == []


def test_drift_checker_catches_undocumented_series():
    mod = _load_drift_checker()
    counts = mod.exported_families()
    docs = mod.documented_families()
    counts["smg_bogus_series_total"] = 1
    errors = [
        e for e in (
            f"family {n} is exported but missing from the docs table"
            for n in counts if n not in docs
        )
    ]
    assert any("smg_bogus_series_total" in e for e in errors)


# ---- e2e: full gateway + in-proc engine, one /metrics scrape ----


def make_engine():
    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.models.config import tiny_test_config

    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=256, auto_size=False, dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=8, max_seq_len=256, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(4, 8),
            speculative=True, spec_max_draft=6,  # exercise spec-decode series
        ),
        dtype="float32",
        model_id="tiny-test",
    )
    return Engine(cfg)


@pytest.fixture(scope="module")
def gateway():
    from smg_tpu.gateway.server import AppContext, build_app
    from smg_tpu.gateway.worker_client import InProcWorkerClient
    from smg_tpu.gateway.workers import Worker
    from smg_tpu.tokenizer import MockTokenizer

    loop = asyncio.new_event_loop()
    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("tiny-test", MockTokenizer(), default=True)
    engine = make_engine()

    async def _setup():
        client = InProcWorkerClient(engine)
        ctx.registry.add(Worker(worker_id="w0", client=client, model_id="tiny-test"))
        server = TestServer(build_app(ctx))
        tc = TestClient(server)
        await tc.start_server()
        return tc

    import threading

    loop_thread = threading.Thread(target=loop.run_forever, daemon=True)
    loop_thread.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=120)

    tc = run(_setup())

    class Handle:
        pass

    h = Handle()
    h.run = run
    h.client = tc
    h.ctx = ctx
    h.engine = engine
    yield h
    run(tc.close())
    loop.call_soon_threadsafe(loop.stop)
    engine.stop()


# repetitive 24-token prompt: crosses a 16-token page (radix-cacheable) and
# gives prompt-lookup speculation n-gram matches to draft from
REPETITIVE_PROMPT = "w5 w6 w7 w8 " * 6


def _completion(gateway, prompt=REPETITIVE_PROMPT, max_tokens=24):
    async def go():
        resp = await gateway.client.post(
            "/v1/completions",
            json={"model": "tiny-test", "prompt": prompt.strip(),
                  "max_tokens": max_tokens, "temperature": 0, "ignore_eos": True},
        )
        return resp.status, await resp.json()

    return gateway.run(go())


def _scrape(gateway) -> str:
    async def go():
        resp = await gateway.client.get("/metrics")
        assert resp.status == 200
        return await resp.text()

    return gateway.run(go())


def test_metrics_exports_engine_series_from_one_registry(gateway):
    status, body = _completion(gateway)
    assert status == 200, body
    status, _ = _completion(gateway)  # identical → radix prefix hit
    assert status == 200

    text = _scrape(gateway)

    # single registry: gateway series and engine series in one scrape
    assert metric_value(text, "smg_requests_total",
                        {"route": "/v1/completions", "status": "200"}) >= 2.0
    assert metric_value(text, "smg_time_to_first_token_seconds_count",
                        {"route": "/v1/completions"}) >= 2.0
    assert metric_value(text, "smg_prompt_tokens_total") >= 48.0
    assert metric_value(text, "smg_generated_tokens_total") >= 48.0

    # step latency histogram, split by phase
    assert metric_value(text, "smg_engine_step_duration_seconds_count",
                        {"phase": "step"}) > 0
    assert metric_value(text, "smg_engine_step_duration_seconds_count",
                        {"phase": "prefill"}) > 0
    assert metric_value(text, "smg_engine_step_duration_seconds_count",
                        {"phase": "decode"}) > 0
    # token throughput: each request's first token comes out of the prefill
    # step, so decode counts max_tokens - 1 per request
    assert metric_value(text, "smg_engine_prefill_tokens_total") > 0
    assert metric_value(text, "smg_engine_decode_tokens_total") >= 46.0
    # page pool
    assert metric_value(text, "smg_engine_kv_total_pages") == 256.0
    assert metric_value(text, "smg_engine_kv_free_pages") > 0
    assert metric_value(text, "smg_engine_kv_page_utilization") is not None
    assert metric_value(text, "smg_engine_batch_occupancy") is not None
    # radix cache: first request misses, second hits the shared prefix
    assert metric_value(text, "smg_engine_radix_miss_pages_total") > 0
    assert metric_value(text, "smg_engine_radix_hit_pages_total") > 0
    assert metric_value(text, "smg_engine_cached_prompt_tokens_total") > 0
    assert metric_value(text, "smg_engine_radix_cached_pages") > 0
    # speculative decoding on a repetitive context drafts (and accepts)
    assert metric_value(text, "smg_engine_spec_drafted_tokens_total",
                        {"tier": "ngram"}) > 0
    assert metric_value(text, "smg_engine_spec_accepted_tokens_total",
                        {"tier": "ngram"}) is not None
    # finish accounting
    assert metric_value(text, "smg_engine_requests_finished_total",
                        {"reason": "length"}) >= 2.0


def test_gateway_and_engine_agree_on_cached_tokens(gateway):
    """Satellite: smg_cached_prompt_tokens_total (gateway) and
    smg_engine_cached_prompt_tokens_total (engine) count one source of truth
    — the scheduler's admission-time radix accounting."""
    _completion(gateway)
    _completion(gateway)
    text = _scrape(gateway)
    gw = metric_value(text, "smg_cached_prompt_tokens_total")
    en = metric_value(text, "smg_engine_cached_prompt_tokens_total")
    assert gw is not None and en is not None and gw > 0
    assert gw == en
    loads = gateway.engine.loads()
    assert loads["cached_prompt_tokens"] == gw


def test_scheduler_endpoint_exposes_engine_stats(gateway):
    _completion(gateway)

    async def go():
        resp = await gateway.client.get("/scheduler")
        assert resp.status == 200
        return await resp.json()

    body = gateway.run(go())
    assert "engine" in body
    w0 = body["engine"]["w0"]
    for key in ("cached_prompt_tokens", "computed_prompt_tokens",
                "cache_hit_rate", "radix_hit_pages", "radix_miss_pages",
                "radix_evicted_pages", "preemptions"):
        assert key in w0, key
    stats = w0["stats"]
    assert stats["num_steps"] > 0
    assert stats["tokens_per_s"] > 0
    assert stats["p95_step_seconds"] >= stats["p50_step_seconds"] >= 0


def test_http_4xx_response_recorded_with_real_status(gateway):
    """Satellite: an inference handler returning 400 without raising must
    count as status="400", not "200" (track_request only wraps
    INFERENCE_ROUTES, and h_chat returns _error(400) on a bad body)."""

    async def go():
        resp = await gateway.client.post(
            "/v1/chat/completions", data=b"{not json",
            headers={"Content-Type": "application/json"},
        )
        return resp.status

    assert gateway.run(go()) == 400
    text = _scrape(gateway)
    assert metric_value(text, "smg_requests_total",
                        {"route": "/v1/chat/completions", "status": "400"}) == 1.0
    assert metric_value(text, "smg_requests_total",
                        {"route": "/v1/chat/completions", "status": "200"}) is None
