import pytest

from smg_tpu.protocols import (
    ChatCompletionRequest,
    ChatMessage,
    CompletionRequest,
    SamplingParams,
)
from smg_tpu.protocols.generate import GenerateRequest


def test_sampling_defaults_valid():
    sp = SamplingParams()
    sp.validate()
    assert not sp.is_greedy
    assert SamplingParams(temperature=0.0).is_greedy


@pytest.mark.parametrize(
    "bad",
    [
        dict(top_p=0.0),
        dict(top_p=1.5),
        dict(top_k=0),
        dict(temperature=-1.0),
        dict(repetition_penalty=0.0),
        dict(n=0),
    ],
)
def test_sampling_rejects_invalid(bad):
    with pytest.raises(ValueError):
        SamplingParams(**bad).validate()


def test_chat_request_to_sampling_params():
    req = ChatCompletionRequest(
        model="m",
        messages=[ChatMessage(role="user", content="hi")],
        temperature=0.5,
        max_tokens=32,
        stop="END",
    )
    sp = req.to_sampling_params(default_max_tokens=128)
    assert sp.temperature == 0.5
    assert sp.max_new_tokens == 32
    assert sp.stop == ["END"]


def test_chat_request_max_completion_tokens_wins():
    req = ChatCompletionRequest(
        model="m",
        messages=[ChatMessage(role="user", content="hi")],
        max_tokens=32,
        max_completion_tokens=64,
    )
    assert req.to_sampling_params(10).max_new_tokens == 64


def test_chat_request_tolerates_vendor_extensions():
    req = ChatCompletionRequest.model_validate(
        {
            "model": "m",
            "messages": [{"role": "user", "content": "hi"}],
            "some_vendor_field": {"x": 1},
        }
    )
    assert req.messages[0].content == "hi"


def test_completion_request_default_max_tokens():
    req = CompletionRequest(model="m", prompt="x", max_tokens=None)
    assert req.to_sampling_params(99).max_new_tokens == 99


def test_generate_request_sampling():
    req = GenerateRequest.model_validate(
        {"text": "hello", "sampling_params": {"max_new_tokens": 4, "temperature": 0.0}}
    )
    sp = req.to_sampling_params(128)
    assert sp.is_greedy and sp.max_new_tokens == 4
