"""MCP orchestration depth (VERDICT r4 next-round #3): approval flow that
pauses/resumes the Responses tool loop, per-tenant server inventory,
sessions with TTL, multi-server routing with collision detection, and the
typed error taxonomy (reference: ``crates/mcp`` + ``tool_loop.rs:41-50``)."""

import asyncio
import json

import pytest

from smg_tpu.gateway.responses import ResponsesHandler
from smg_tpu.gateway.router import Router, RouterConfig
from smg_tpu.gateway.worker_client import WorkerClient, WorkerStreamChunk
from smg_tpu.gateway.workers import Worker, WorkerRegistry
from smg_tpu.mcp import (
    ApprovalManager,
    ApprovalPolicy,
    Decision,
    LocalToolServer,
    McpInventory,
    McpRegistry,
    PolicyRule,
    SessionManager,
    ServerAccessDenied,
    ToolCollision,
    ToolDenied,
    ToolNotFound,
    TrustLevel,
)
from smg_tpu.policies import PolicyRegistry
from smg_tpu.protocols.responses import ResponsesRequest
from smg_tpu.storage import MemoryStorage
from smg_tpu.tokenizer import MockTokenizer
from smg_tpu.tokenizer.registry import TokenizerRegistry


# ---- policy engine ----


def test_policy_rules_first_match_and_trust():
    p = (ApprovalPolicy(default=Decision.ALLOW)
         .add_rule(PolicyRule(server="prod*", tool="delete_*",
                              decision=Decision.DENY, reason="no deletes"))
         .add_rule(PolicyRule(server="prod*", decision=Decision.REQUIRE_APPROVAL))
         .set_server_trust("sandbox", TrustLevel.TRUSTED)
         .set_server_trust("sketchy", TrustLevel.UNTRUSTED))
    assert p.evaluate("prod-db", "delete_rows") == (Decision.DENY, "no deletes")
    assert p.evaluate("prod-db", "read_rows")[0] is Decision.REQUIRE_APPROVAL
    assert p.evaluate("sandbox", "anything")[0] is Decision.ALLOW
    assert p.evaluate("sketchy", "anything")[0] is Decision.REQUIRE_APPROVAL
    assert p.evaluate("other", "anything")[0] is Decision.ALLOW


def test_policy_read_only_condition():
    p = ApprovalPolicy().add_rule(
        PolicyRule(tool="*", decision=Decision.REQUIRE_APPROVAL,
                   only_if_write=True)
    )
    assert p.evaluate("s", "t", read_only=True)[0] is Decision.ALLOW
    assert p.evaluate("s", "t", read_only=False)[0] is Decision.REQUIRE_APPROVAL


# ---- approval manager ----


def test_approval_manager_park_decide_audit():
    mgr = ApprovalManager(
        ApprovalPolicy().add_rule(
            PolicyRule(server="s", decision=Decision.REQUIRE_APPROVAL))
    )
    pending = mgr.check("s", "t", '{"a": 1}', request_id="r1")
    assert pending is not None and mgr.has_pending(pending.key)
    got = mgr.decide(pending.key, approve=True)
    assert got.tool == "t" and not mgr.has_pending(pending.key)
    # unknown key is a typed error
    from smg_tpu.mcp import ApprovalNotFound

    with pytest.raises(ApprovalNotFound):
        mgr.decide("mcpr_nope", approve=True)
    kinds = [e.decision for e in mgr.audit.tail()]
    assert kinds == ["pending", "approved"]


def test_approval_manager_deny_and_force():
    mgr = ApprovalManager(
        ApprovalPolicy().add_rule(PolicyRule(server="bad", decision=Decision.DENY))
    )
    with pytest.raises(ToolDenied):
        mgr.check("bad", "t", "{}")
    # ALLOW + force_approval (request-level require_approval=always) parks
    assert mgr.check("good", "t", "{}", force_approval=True) is not None


def test_approval_timeout_eviction():
    mgr = ApprovalManager(
        ApprovalPolicy(default=Decision.REQUIRE_APPROVAL), timeout=0.0
    )
    pending = mgr.check("s", "t", "{}")
    assert pending is not None
    assert mgr.pending_count() == 0  # evicted instantly at timeout=0
    assert any(e.decision == "expired" for e in mgr.audit.tail())


# ---- sessions ----


def test_session_manager_ttl_and_registry_change():
    async def go():
        sm = SessionManager(ttl=1e9)
        reg = McpRegistry()
        srv = LocalToolServer("a")
        srv.register("t", lambda: "x")
        reg.add(srv)
        s1 = await sm.get_or_create("conv1", reg)
        s2 = await sm.get_or_create("conv1", reg)
        assert s1 is s2 and sm.count == 1
        # same id, different server set -> fresh session (no stale catalog)
        reg2 = McpRegistry()
        reg2.add(srv)
        reg2.add(LocalToolServer("b"))
        s3 = await sm.get_or_create("conv1", reg2)
        assert s3 is not s1
        # TTL eviction
        sm.ttl = 0.0
        await sm.get_or_create("conv2", reg)
        assert sm.get("conv1") is None

    asyncio.run(go())


# ---- inventory / tenancy ----


def test_inventory_tenant_views():
    inv = McpInventory()
    shared = LocalToolServer("shared")
    priv = LocalToolServer("acme-internal")
    inv.add_server(shared)
    inv.add_server(priv, tenants=["acme"])
    assert inv.servers_for("acme") == ["acme-internal", "shared"]
    assert inv.servers_for("other") == ["shared"]
    assert inv.servers_for(None) == ["shared"]
    inv.check_access("acme", "acme-internal")
    with pytest.raises(ServerAccessDenied):
        inv.check_access("other", "acme-internal")
    reg = inv.registry_for("other")
    assert reg.servers == ["shared"]


# ---- multi-server routing + collisions ----


def test_registry_collision_and_qualified_names():
    async def go():
        a, b = LocalToolServer("a"), LocalToolServer("b")
        a.register("search", lambda q: f"a:{q}")
        b.register("search", lambda q: f"b:{q}")
        b.register("only_b", lambda: "ok")
        reg = McpRegistry()
        reg.add(a)
        reg.add(b)
        assert await reg.collisions() == {"search": ["a", "b"]}
        with pytest.raises(ToolCollision) as ei:
            await reg.call_tool("search", {"q": "x"})
        assert ei.value.servers == ["a", "b"]
        # qualified names always route
        assert await reg.call_tool("a.search", {"q": "x"}) == "a:x"
        assert await reg.call_tool("b.search", {"q": "x"}) == "b:x"
        assert await reg.call_tool("only_b", {}) == "ok"
        with pytest.raises(ToolNotFound):
            await reg.call_tool("nope", {})

    asyncio.run(go())


# ---- e2e: approval pauses the Responses loop and resumes on approve ----


class TextTokenizer(MockTokenizer):
    """Chunked text round-trip (same trick as test_agentic)."""

    def __init__(self):
        super().__init__()
        self.pieces = {}
        self._next = 10

    def decode(self, ids, skip_special_tokens=True):
        return "".join(self.pieces.get(int(t), "") for t in ids)

    def encode(self, text, add_special_tokens=False):
        ids = []
        for i in range(0, len(text), 4):
            tid = self._next
            self._next += 1
            self.pieces[tid] = text[i : i + 4]
            ids.append(tid)
        return ids


class ScriptedClient(WorkerClient):
    def __init__(self, scripts, tokenizer):
        self.scripts = scripts
        self.tokenizer = tokenizer
        self.turn = 0

    async def generate(self, req):
        text = self.scripts[min(self.turn, len(self.scripts) - 1)]
        self.turn += 1
        ids = self.tokenizer.encode(text)
        yield WorkerStreamChunk(
            rid=req.rid, token_ids=ids, finished=True, finish_reason="stop",
            prompt_tokens=len(req.input_ids), output_tokens=len(ids),
        )

    async def abort(self, rid):
        return True


def _handler(scripts, approvals=None, inventory=None, mcp=None, storage=None):
    tok = TextTokenizer()
    registry = WorkerRegistry()
    registry.add(Worker(worker_id="w0", client=ScriptedClient(scripts, tok),
                        model_id="scripted"))
    tokenizers = TokenizerRegistry()
    tokenizers.register("scripted", tok, default=True)
    router = Router(registry, PolicyRegistry(default="round_robin"),
                    tokenizers, RouterConfig())
    return ResponsesHandler(router, storage=storage or MemoryStorage(),
                            mcp=mcp, inventory=inventory, approvals=approvals)


def test_responses_approval_pause_and_resume():
    calls_made = []
    srv = LocalToolServer("calc")
    srv.register("add", lambda a, b: (calls_made.append((a, b)), {"sum": a + b})[1],
                 "adds", {"type": "object"})
    mcp = McpRegistry()
    mcp.add(srv)
    approvals = ApprovalManager(
        ApprovalPolicy().add_rule(
            PolicyRule(server="calc", decision=Decision.REQUIRE_APPROVAL))
    )
    h = _handler(
        ['{"name": "add", "arguments": {"a": 2, "b": 5}}', "the sum is seven"],
        approvals=approvals, mcp=mcp,
    )

    async def go():
        r1 = await h.create(ResponsesRequest(
            model="scripted", input="add two and five", temperature=0.0))
        # paused: approval request item, tool NOT executed
        kinds1 = [o["type"] for o in r1.output]
        assert "mcp_approval_request" in kinds1
        assert "function_call_output" not in kinds1
        assert calls_made == []
        ar = next(o for o in r1.output if o["type"] == "mcp_approval_request")
        assert ar["name"] == "add" and ar["server_label"] == "calc"
        assert json.loads(ar["arguments"]) == {"a": 2, "b": 5}

        # resume with approval -> tool runs, loop continues to the answer
        r2 = await h.create(ResponsesRequest(
            model="scripted", previous_response_id=r1.id, temperature=0.0,
            input=[{"type": "mcp_approval_response",
                    "approval_request_id": ar["id"], "approve": True}]))
        kinds2 = [o["type"] for o in r2.output]
        assert "mcp_call" in kinds2
        call = next(o for o in r2.output if o["type"] == "mcp_call")
        assert '"sum": 7' in call["output"] and call["error"] is None
        assert calls_made == [(2, 5)]
        assert "message" in kinds2  # model answered after the tool result
        return True

    assert asyncio.run(go())


def test_responses_approval_denied_never_executes():
    calls_made = []
    srv = LocalToolServer("calc")
    srv.register("add", lambda a, b: calls_made.append((a, b)) or "x",
                 "adds", {"type": "object"})
    mcp = McpRegistry()
    mcp.add(srv)
    h = _handler(
        ['{"name": "add", "arguments": {"a": 1, "b": 1}}', "understood"],
        approvals=ApprovalManager(ApprovalPolicy(default=Decision.REQUIRE_APPROVAL)),
        mcp=mcp,
    )

    async def go():
        r1 = await h.create(ResponsesRequest(
            model="scripted", input="add", temperature=0.0))
        ar = next(o for o in r1.output if o["type"] == "mcp_approval_request")
        r2 = await h.create(ResponsesRequest(
            model="scripted", previous_response_id=r1.id, temperature=0.0,
            input=[{"type": "mcp_approval_response",
                    "approval_request_id": ar["id"], "approve": False}]))
        call = next(o for o in r2.output if o["type"] == "mcp_call")
        assert call["error"] == "approval denied by user"
        assert calls_made == []
        return True

    assert asyncio.run(go())


def test_responses_stateless_resume_rebuilds_pending():
    """A different gateway instance (fresh ApprovalManager) resolves the
    approval from the stored response chain."""
    calls_made = []
    srv = LocalToolServer("calc")
    srv.register("add", lambda a, b: (calls_made.append((a, b)), {"sum": a + b})[1])
    mcp = McpRegistry()
    mcp.add(srv)
    storage = MemoryStorage()
    h1 = _handler(['{"name": "add", "arguments": {"a": 3, "b": 4}}', "done"],
                  approvals=ApprovalManager(
                      ApprovalPolicy(default=Decision.REQUIRE_APPROVAL)),
                  mcp=mcp, storage=storage)

    async def go():
        r1 = await h1.create(ResponsesRequest(
            model="scripted", input="add", temperature=0.0))
        ar = next(o for o in r1.output if o["type"] == "mcp_approval_request")
        # "other instance": same storage, FRESH approval manager
        h2 = _handler(["done"], approvals=ApprovalManager(
            ApprovalPolicy(default=Decision.ALLOW)), mcp=mcp, storage=storage)
        r2 = await h2.create(ResponsesRequest(
            model="scripted", previous_response_id=r1.id, temperature=0.0,
            input=[{"type": "mcp_approval_response",
                    "approval_request_id": ar["id"], "approve": True}]))
        call = next(o for o in r2.output if o["type"] == "mcp_call")
        assert '"sum": 7' in call["output"]
        assert calls_made == [(3, 4)]
        return True

    assert asyncio.run(go())


def test_responses_request_level_require_approval():
    """OpenAI-shape require_approval=always on a request-level mcp tool
    parks the call even though policy allows."""
    h = _handler(['{"name": "echo", "arguments": {"v": 1}}', "ok"])
    # request-level server: LocalToolServer can't ride the request (that
    # needs a URL) — register it via inventory as a tenant-visible server
    inv = McpInventory()
    srv = LocalToolServer("req-srv")
    srv.register("echo", lambda v: str(v))
    inv.add_server(srv)
    h.inventory = inv

    async def go():
        r = await h.create(ResponsesRequest(
            model="scripted", input="echo", temperature=0.0,
            tools=[{"type": "mcp", "server_label": "req-srv",
                    "server_url": "local://req-srv",
                    "require_approval": "always"}]))
        return [o["type"] for o in r.output]

    kinds = asyncio.run(go())
    assert "mcp_approval_request" in kinds


def test_responses_mcp_list_tools_once_per_chain():
    srv = LocalToolServer("calc")
    srv.register("add", lambda a, b: "2")
    mcp = McpRegistry()
    mcp.add(srv)
    storage = MemoryStorage()
    h = _handler(["hello", "again"], mcp=mcp, storage=storage)

    async def go():
        r1 = await h.create(ResponsesRequest(model="scripted", input="hi",
                                             temperature=0.0))
        r2 = await h.create(ResponsesRequest(model="scripted", input="more",
                                             previous_response_id=r1.id,
                                             temperature=0.0))
        return r1.output, r2.output

    o1, o2 = asyncio.run(go())
    assert [o["type"] for o in o1 if o["type"] == "mcp_list_tools"] == ["mcp_list_tools"]
    lt = next(o for o in o1 if o["type"] == "mcp_list_tools")
    assert lt["server_label"] == "calc"
    assert [t["name"] for t in lt["tools"]] == ["add"]
    # second turn in the chain: label already listed, no repeat item
    assert all(o["type"] != "mcp_list_tools" for o in o2)


def test_responses_tenant_isolation():
    """Tenant B must not see (or call) tenant A's servers."""
    inv = McpInventory()
    a_srv = LocalToolServer("a-tools")
    a_srv.register("secret", lambda: "classified")
    inv.add_server(a_srv, tenants=["tenant-a"])
    h = _handler(["plain answer"], inventory=inv)

    async def go():
        ra = await h.create(ResponsesRequest(model="scripted", input="x",
                                             temperature=0.0), tenant="tenant-a")
        hb = _handler(["plain answer"], inventory=inv)
        rb = await hb.create(ResponsesRequest(model="scripted", input="x",
                                              temperature=0.0), tenant="tenant-b")
        return ra.output, rb.output

    oa, ob = asyncio.run(go())
    assert any(o["type"] == "mcp_list_tools" for o in oa)
    assert all(o["type"] != "mcp_list_tools" for o in ob)
