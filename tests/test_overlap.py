"""Overlap-pipeline parity: the one-step-lookahead scheduler must produce
token streams BYTE-IDENTICAL to the synchronous path in every scenario —
greedy, seeded sampling, stop-string rollback mid-lookahead, abort of an
in-flight request, the pipelined speculative schedule, and structured-output
forced sync.  Each test runs the same workload through a fresh engine with
``overlap_schedule`` on and off (fresh engines so the sampling-key counter
starts identically) and compares full per-request streams."""

import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


def make_engine(overlap: bool, num_pages=128, max_batch=8, max_seq_len=256,
                **sched_kw) -> Engine:
    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=num_pages, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=max_batch,
            max_seq_len=max_seq_len,
            max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64),
            decode_batch_buckets=(4, 8),
            overlap_schedule=overlap,
            **sched_kw,
        ),
        dtype="float32",
    )
    return Engine(cfg, tokenizer=MockTokenizer())


def run_streams(engine: Engine, jobs: list) -> dict:
    """Submit ``jobs`` = [(rid, prompt_ids, sampling)] concurrently, drive
    the step loop inline to completion, and return the full stream per rid:
    (token_ids, text, finish_reason, matched_stop, logprobs)."""
    chunks: dict[str, list] = {rid: [] for rid, _, _ in jobs}
    done: set[str] = set()

    def cb(out):
        chunks[out.rid].append(out)
        if out.finished:
            done.add(out.rid)

    for rid, prompt, sampling in jobs:
        engine.submit(prompt, sampling, rid=rid, on_output=cb)
    for _ in range(5000):
        if len(done) == len(jobs):
            # drain the pipeline (a kept lookahead may still be in flight)
            while engine.scheduler.has_work():
                engine.step()
            break
        engine.step()
    else:
        raise TimeoutError(f"jobs stuck: {engine.loads()}")
    out = {}
    for rid, _, _ in jobs:
        toks = [t for c in chunks[rid] for t in c.new_token_ids]
        text = "".join(c.text_delta for c in chunks[rid])
        lps = [round(x, 4) for c in chunks[rid] for x in c.logprobs]
        last = chunks[rid][-1]
        out[rid] = (toks, text, last.finish_reason, last.matched_stop, lps)
    return out


def assert_parity(jobs, **engine_kw):
    a = run_streams(make_engine(True, **engine_kw), jobs)
    b = run_streams(make_engine(False, **engine_kw), jobs)
    assert a == b, f"overlap diverged from sync:\n{a}\nvs\n{b}"
    return a


def greedy(max_new=8, **kw) -> SamplingParams:
    return SamplingParams(temperature=0.0, max_new_tokens=max_new,
                          ignore_eos=True, **kw)


def test_greedy_parity_concurrent_batch():
    jobs = [
        (f"g{i}", list(range(5 + i, 25 + 3 * i)), greedy(6 + 2 * i))
        for i in range(4)
    ]
    assert_parity(jobs)


def test_greedy_parity_with_horizon():
    jobs = [(f"h{i}", list(range(10 + i, 40 + i)), greedy(13)) for i in range(3)]
    assert_parity(jobs, decode_horizon=4)


def test_seeded_sampling_parity():
    jobs = [
        ("s0", list(range(40, 80)),
         SamplingParams(temperature=0.9, top_k=40, top_p=0.95,
                        max_new_tokens=12, ignore_eos=True)),
        ("s1", list(range(90, 120)),
         SamplingParams(temperature=0.7, min_p=0.05, max_new_tokens=10,
                        ignore_eos=True)),
        ("s2", list(range(130, 150)),
         SamplingParams(temperature=1.1, frequency_penalty=0.4,
                        presence_penalty=0.2, max_new_tokens=9,
                        ignore_eos=True)),
    ]
    assert_parity(jobs)


def test_eos_and_stop_token_parity():
    # natural EOS finishes (ignore_eos off) and stop_token_ids both cut the
    # stream mid-flight, which is exactly what invalidates a lookahead
    probe = run_streams(
        make_engine(False), [("p", list(range(5, 15)), greedy(6))]
    )["p"][0]
    stop_tok = probe[3]
    jobs = [
        ("e0", list(range(5, 15)),
         SamplingParams(temperature=0.0, max_new_tokens=32)),
        ("e1", list(range(5, 15)),
         SamplingParams(temperature=0.0, max_new_tokens=32, ignore_eos=True,
                        stop_token_ids=[stop_tok])),
    ]
    res = assert_parity(jobs)
    assert res["e1"][2] == "stop" and res["e1"][3] == stop_tok


def test_stop_string_rollback_mid_lookahead():
    # the stop string is found at the ENGINE layer after the scheduler step
    # returned, with the next lookahead frame already in flight: the engine
    # rolls back trailing tokens and finishes the request, and the kept
    # frame must be discarded without corrupting any other stream
    probe = run_streams(
        make_engine(False), [("p", list(range(60, 90)), greedy(8))]
    )["p"][0]
    stop_word = f"w{probe[2]}"
    jobs = [
        ("r0", list(range(60, 90)),
         SamplingParams(temperature=0.0, max_new_tokens=16, ignore_eos=True,
                        stop=[stop_word])),
        ("r1", list(range(7, 31)), greedy(14)),  # rides alongside, unaffected
    ]
    res = assert_parity(jobs)
    assert res["r0"][2] == "stop" and res["r0"][3] == stop_word
    assert not res["r0"][1].endswith(stop_word)


def test_stop_string_rollback_with_horizon():
    probe = run_streams(
        make_engine(False, decode_horizon=4),
        [("p", list(range(60, 90)), greedy(8))],
    )["p"][0]
    stop_word = f"w{probe[2]}"
    jobs = [
        ("r0", list(range(60, 90)),
         SamplingParams(temperature=0.0, max_new_tokens=16, ignore_eos=True,
                        stop=[stop_word])),
        ("r1", list(range(7, 31)), greedy(14)),
    ]
    res = assert_parity(jobs, decode_horizon=4)
    assert res["r0"][2] == "stop"


def test_abort_of_inflight_request():
    eng = make_engine(True)
    got: dict[str, list] = {"a": [], "b": []}
    eng.submit(list(range(5, 25)), greedy(64), rid="a",
               on_output=lambda o: got["a"].append(o))
    eng.submit(list(range(30, 55)), greedy(10), rid="b",
               on_output=lambda o: got["b"].append(o))
    for _ in range(3):
        eng.step()
    assert eng.abort("a")
    for _ in range(200):
        if got["b"] and got["b"][-1].finished:
            break
        eng.step()
    assert got["b"][-1].finished and got["b"][-1].finish_reason == "length"
    # the aborted request's lanes went stale with its frame in flight; the
    # survivor's stream must equal a run where "a" never existed past abort
    while eng.scheduler.has_work():
        eng.step()
    assert eng.scheduler.inflight is None
    assert all(s is None for s in eng.scheduler.slots)
    # no page leak: everything not held by the radix cache is back in the pool
    sched = eng.scheduler
    held = sched.radix.num_cached_pages if sched.radix else 0
    assert sched.pool.free_count + held == eng.runner.spec.num_pages - 1


def test_speculative_pipelines_with_parity():
    # spec no longer forces sync: the batched verify frame stays in flight
    # across steps (drafting/detokenize overlap the device pass), and the
    # overlap-on stream must still be byte-identical to overlap-off
    rep = [5, 6, 7, 8] * 8
    jobs = [("sp", rep, greedy(16))]
    res = assert_parity(jobs, speculative=True, spec_max_draft=6)
    eng = make_engine(True, speculative=True, spec_max_draft=6)
    streams = run_streams(eng, jobs)
    assert streams == res
    assert eng.scheduler.num_lookahead_kept > 0  # the spec pipeline engaged
    assert eng.scheduler.inflight is None  # drained clean
    assert eng.scheduler.num_spec_drafted > 0  # spec really ran
    assert eng.scheduler.num_spec_accepted > 0  # repetitive prompt accepts


def test_structured_output_forces_sync():
    # grammar-masked requests need a host-derived vocab mask per token
    # (depends on last step's token), so no lookahead may be launched while
    # one is active — but the stream must still match the sync path
    jobs = [
        ("j0", list(range(20, 50)),
         SamplingParams(temperature=0.0, max_new_tokens=6, ignore_eos=True,
                        regex=r"w[0-9 ]*")),
        ("j1", list(range(70, 95)), greedy(6)),
    ]
    res = assert_parity(jobs)
    assert res["j0"][0]  # produced tokens under the grammar
    eng = make_engine(True)
    run_streams(eng, jobs)
    assert eng.scheduler.num_lookahead_kept == 0


def test_lookahead_engages_and_counters_exposed():
    eng = make_engine(True)
    run_streams(eng, [(f"l{i}", list(range(5 + i, 30 + i)), greedy(16))
                      for i in range(3)])
    loads = eng.loads()
    assert loads["lookahead_kept"] > 0
    assert "lookahead_discarded" in loads
    # sync engines never engage the pipeline
    eng2 = make_engine(False)
    run_streams(eng2, [("x", list(range(5, 30)), greedy(8))])
    assert eng2.loads()["lookahead_kept"] == 0


def test_overlap_metrics_recorded():
    from prometheus_client import generate_latest

    eng = make_engine(True)
    probe = run_streams(eng, [("m", list(range(5, 30)), greedy(12))])
    # a stop-token finish is UNPREDICTED at lookahead-launch time (unlike a
    # length finish, which suppresses the launch), so it forces a discard
    stop_tok = probe["m"][0][4]
    run_streams(eng, [
        ("d", list(range(5, 30)),
         SamplingParams(temperature=0.0, max_new_tokens=32, ignore_eos=True,
                        stop_token_ids=[stop_tok])),
        ("d2", list(range(31, 55)), greedy(20)),
    ])
    text = generate_latest(eng.metrics.registry).decode()
    assert 'smg_engine_lookahead_launches_total{outcome="kept"}' in text
    assert 'smg_engine_lookahead_launches_total{outcome="discarded"}' in text
    assert "smg_engine_deferred_fetch_seconds" in text
    assert "smg_engine_overlap_host_busy_seconds_total" in text
    assert "smg_engine_overlap_device_wait_seconds_total" in text
    assert eng.scheduler.num_lookahead_discarded > 0


def test_submission_behind_kept_lookahead():
    # submit a second request while the first's lookahead frame is in
    # flight: sync admits before decoding, so the kept frame must be
    # discarded and the combined batch must match the sync schedule
    def run(overlap):
        eng = make_engine(overlap)
        got: dict[str, list] = {"a": [], "b": []}
        eng.submit(list(range(5, 25)), greedy(20), rid="a",
                   on_output=lambda o: got["a"].append(o))
        for _ in range(4):
            eng.step()
        eng.submit(list(range(40, 70)), greedy(12), rid="b",
                   on_output=lambda o: got["b"].append(o))
        for _ in range(300):
            if all(v and v[-1].finished for v in got.values()):
                break
            eng.step()
        while eng.scheduler.has_work():
            eng.step()
        return {
            rid: [t for o in v for t in o.new_token_ids]
            for rid, v in got.items()
        }

    assert run(True) == run(False)


def test_preemption_under_page_pressure_parity():
    # tight page pool: growth forces eviction/preemption, which the
    # lookahead capacity precheck must route through the sync path
    jobs = [(f"p{i}", list(range(5 + 17 * i, 37 + 17 * i)), greedy(24))
            for i in range(4)]
    a = run_streams(make_engine(True, num_pages=24, max_batch=4), jobs)
    b = run_streams(make_engine(False, num_pages=24, max_batch=4), jobs)
    assert a == b


def test_flush_cache_with_stale_inflight_frame():
    eng = make_engine(True)
    run_streams(eng, [("f", list(range(5, 30)), greedy(6))])
    # pipeline drained by run_streams; force a frame then finish everything
    assert eng.flush_cache()
    r = eng.generate(prompt_ids=list(range(5, 30)), sampling=greedy(6))
    assert len(r.token_ids) == 6


def test_engine_stop_drops_inflight():
    eng = make_engine(True)
    eng.start()
    eng.submit(list(range(5, 25)), greedy(64), rid="s")
    import time

    time.sleep(0.3)  # let the loop launch frames
    eng.stop()
    assert eng.scheduler.inflight is None


@pytest.mark.slow  # subsumed by the temp-0.8 variant below (key-sensitive)
def test_chunked_prefill_parity_greedy():
    # a multi-chunk prompt admits under the per-step budget (64) while a
    # short one decodes: the resumable-prefill steps are fold-free, so the
    # pipeline keeps lookahead frames across them — streams must still be
    # byte-identical to the sync path
    jobs = [
        ("long", list(range(5, 155)), greedy(8)),
        ("c0", list(range(200, 230)), greedy(14)),
    ]
    assert_parity(jobs)


def test_chunked_prefill_parity_sampled():
    # temp 0.8: any key-fold ordering slip between the chunked prefill and
    # the chained decode launches flips the sampled streams
    jobs = [
        ("long", list(range(5, 185)),
         SamplingParams(temperature=0.8, top_k=40, max_new_tokens=10,
                        ignore_eos=True)),
        ("c0", list(range(200, 240)),
         SamplingParams(temperature=0.8, max_new_tokens=12, ignore_eos=True)),
        ("c1", list(range(250, 275)), greedy(9)),
    ]
    assert_parity(jobs, decode_horizon=2)


@pytest.mark.slow  # legacy-policy variant; budgeted-vs-legacy parity also
# rides tests/test_chunked_prefill.py in tier-1
def test_chunked_prefill_parity_legacy_policy():
    # the legacy drain-the-queue policy must keep its own overlap/sync parity
    jobs = [
        ("long", list(range(5, 155)), greedy(8)),
        ("c0", list(range(200, 230)),
         SamplingParams(temperature=0.9, max_new_tokens=8, ignore_eos=True)),
    ]
    assert_parity(jobs, prefill_mix_policy="throughput")


def test_admission_on_slot_freed_by_inflight_finish_parity():
    # max_batch 2: request "b" waits for a slot that only frees when "a"
    # finishes INSIDE the in-flight frame.  Sync admits "b" the same step
    # the slot frees; the overlap prefill phase must therefore run with
    # post-consume capacity (regression: admission ran pre-consume, saw the
    # free slot one step late, and shifted the sampling-key fold order)
    jobs = [
        ("a", list(range(5, 15)),
         SamplingParams(temperature=0.8, max_new_tokens=3, ignore_eos=True)),
        ("c", list(range(30, 50)),
         SamplingParams(temperature=0.8, max_new_tokens=20, ignore_eos=True)),
        ("b", list(range(60, 85)),
         SamplingParams(temperature=0.8, max_new_tokens=8, ignore_eos=True)),
    ]
    assert_parity(jobs, max_batch=2)


def test_admission_on_pages_freed_by_inflight_finish_parity():
    # same shape under PAGE pressure: "b" back-pressures on pages released
    # by "a"'s in-frame finish
    jobs = [
        ("a", list(range(5, 40)),
         SamplingParams(temperature=0.8, max_new_tokens=4, ignore_eos=True)),
        ("c", list(range(50, 80)),
         SamplingParams(temperature=0.8, max_new_tokens=16, ignore_eos=True)),
        ("b", list(range(100, 140)),
         SamplingParams(temperature=0.8, max_new_tokens=6, ignore_eos=True)),
    ]
    assert_parity(jobs, num_pages=16, max_batch=4, max_seq_len=128)


def test_lookahead_survives_admission_over_budget():
    # historically ANY waiting request forced the pipeline sync (kept
    # required an empty queue).  Now: a long prompt mid-resumable-prefill
    # consumes the whole per-step budget, a second prompt waits over budget
    # — and the running lane's lookahead frames stay KEPT through both.
    from smg_tpu.engine.request import RequestStatus

    eng = make_engine(True)
    got: list = []
    eng.submit(list(range(5, 30)), greedy(48), rid="a",
               on_output=lambda o: got.append(o))
    for _ in range(3):  # admit + prime the pipeline
        eng.step()
    eng.submit(list(range(40, 220)), greedy(4), rid="long")  # 3 chunks @ 64
    eng.submit(list(range(300, 330)), greedy(4), rid="w")  # over budget
    sched = eng.scheduler
    kept_while_waiting = 0
    saw_prefilling = False
    for _ in range(4):
        kept0 = sched.num_lookahead_kept
        eng.step()
        lr = sched.requests.get("long")
        if lr is not None and lr.status is RequestStatus.PREFILLING:
            saw_prefilling = True
            assert 0 < lr.prefill_pos < 180
        if sched.num_lookahead_kept > kept0 and sched.waiting:
            kept_while_waiting += 1
    assert saw_prefilling
    assert kept_while_waiting > 0  # the pipeline rode across the admission
    while sched.has_work():
        eng.step()
    assert not sched.requests  # everyone drained to completion


@pytest.mark.slow
@pytest.mark.parametrize("horizon", [1, 2, 4])
def test_exhaustive_parity_sweep(horizon):
    """Randomized stress parity: mixed greedy/sampled/stop/penalty workloads
    at several horizons, staggered finish lengths so lookahead frames get
    invalidated at many different points."""
    import random

    rng = random.Random(horizon)
    jobs = []
    for i in range(6):
        prompt = [rng.randrange(5, 500) for _ in range(rng.randrange(8, 60))]
        if i % 3 == 0:
            sp = greedy(rng.randrange(3, 20))
        elif i % 3 == 1:
            sp = SamplingParams(temperature=0.8, top_k=50,
                                max_new_tokens=rng.randrange(3, 20),
                                ignore_eos=True)
        else:
            sp = SamplingParams(temperature=0.0,
                                max_new_tokens=rng.randrange(6, 24),
                                frequency_penalty=0.3, ignore_eos=True)
        jobs.append((f"x{i}", prompt, sp))
    assert_parity(jobs, decode_horizon=horizon)
