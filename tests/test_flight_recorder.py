"""Flight recorder + SLO accounting: the engine's step-level black box
(`engine/flight_recorder.py`), its auto-dump triggers (quarantine / watchdog
stall / health flip / drain — driven through `smg_tpu/faults.py`, zero
monkeypatching), the DumpFlight RPC / `GET /debug/flight/{worker}` fetch
path, the gateway SLO tracker behind `/debug/slo`, and the TTFT
retry-attribution fix (failover latency must be visible in
`smg_time_to_first_token_seconds`)."""

import asyncio
import json
import threading
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.engine.flight_recorder import (
    SCHEMA_VERSION,
    STEP_RECORD_KEYS,
    FlightRecorder,
)
from smg_tpu.faults import FAULTS
from smg_tpu.gateway.observability import Metrics
from smg_tpu.gateway.server import AppContext, build_app
from smg_tpu.gateway.worker_client import (
    InProcWorkerClient,
    WorkerClient,
    WorkerGenerateRequest,
    WorkerQueueFullError,
    WorkerStreamChunk,
)
from smg_tpu.gateway.workers import Worker, WorkerRegistry
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    FAULTS.clear()


def make_engine(watchdog_secs: float = 0.0, *, flight_kw: dict | None = None,
                **sched_kw) -> Engine:
    sched = dict(
        max_batch_size=4, max_seq_len=128, max_prefill_tokens=32,
        prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(4,),
    )
    sched.update(sched_kw)
    return Engine(
        EngineConfig(
            model=tiny_test_config(),
            cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                              dtype="float32"),
            scheduler=SchedulerConfig(**sched),
            dtype="float32",
            model_id="tiny-flight",
            step_watchdog_secs=watchdog_secs,
            # tests assert on immediate dump sequences; the production
            # default (5s) would suppress the second trigger
            flight_dump_min_interval_secs=0.0,
            **(flight_kw or {}),
        )
    )


def _collector(outs: dict, rid: str):
    def cb(out):
        outs.setdefault(rid, []).append(out)
    return cb


def _drive(eng: Engine, outs: dict, rids: list, max_steps: int = 400) -> None:
    for _ in range(max_steps):
        eng.step()
        if all(rid in outs and any(o.finished for o in outs[rid]) for rid in rids):
            return
    raise AssertionError(f"requests never finished: {list(outs)}")


# ---- ring buffer + timelines (engine-local, inline stepping) ----


def test_ring_buffer_bound_holds_under_churn():
    """The step ring and finished-timeline ring stay at their configured
    bounds no matter how many steps/requests churn through."""
    eng = make_engine(flight_kw=dict(flight_ring_size=16, flight_timeline_keep=8))
    fl = eng.scheduler.flight
    outs: dict = {}
    for batch in range(4):
        rids = [f"r{batch}-{i}" for i in range(4)]
        for rid in rids:
            eng.submit([5 + batch, 6, 7, 8], SamplingParams(
                temperature=0.0, max_new_tokens=6, ignore_eos=True),
                rid=rid, on_output=_collector(outs, rid))
        _drive(eng, outs, rids)
    snap = fl.snapshot()
    assert len(snap["ring"]) == 16  # full and bounded
    assert fl.step_serial > 16  # far more steps happened than the ring holds
    serials = [r["serial"] for r in snap["ring"]]
    assert serials == sorted(serials) and serials[-1] == fl.step_serial
    assert len(snap["timelines"]["finished"]) == 8  # 16 finished, 8 kept
    assert snap["timelines"]["live"] == []
    eng.stop()


def test_timeline_completeness_chunked_prefill_overlap():
    """Under chunked prefill (budget 32, 80-token prompt) with the overlap
    pipeline on, the timeline still reads queued -> admitted -> every
    prefill chunk (final last) -> first token -> finish, with TTFT/ITL/e2e
    computed."""
    eng = make_engine()  # overlap_schedule defaults on
    outs: dict = {}
    # a running stream so the long admission interleaves with decode
    eng.submit([9, 9, 9], SamplingParams(
        temperature=0.0, max_new_tokens=24, ignore_eos=True),
        rid="bg", on_output=_collector(outs, "bg"))
    for _ in range(4):
        eng.step()
    eng.submit(list(range(5, 85)), SamplingParams(
        temperature=0.0, max_new_tokens=4, ignore_eos=True),
        rid="long", on_output=_collector(outs, "long"))
    _drive(eng, outs, ["bg", "long"])
    dump = eng.dump_flight()
    tl = {t["rid"]: t for t in dump["timelines"]["finished"]}["long"]
    kinds = [e["kind"] for e in tl["events"]]
    assert kinds[0] == "queued" and kinds[1] == "admitted"
    chunks = [e for e in tl["events"] if e["kind"] == "prefill_chunk"]
    # 80 tokens / 32-token budget -> 3 chunks, only the last final
    assert len(chunks) == 3
    assert [c["final"] for c in chunks] == [False, False, True]
    assert sum(c["n"] for c in chunks) == 80
    assert kinds.index("first_token") > kinds.index("admitted")
    assert kinds[-1] == "finish" and tl["finish_reason"] == "length"
    assert tl["ttft_s"] > 0 and tl["e2e_s"] >= tl["ttft_s"]
    assert tl["output_tokens"] == 4 and tl["prompt_tokens"] == 80
    assert tl["itl"]["count"] == 3  # 4 tokens -> 3 gaps
    # overlap outcomes recorded in the ring
    outcomes = {r["overlap"] for r in dump["ring"]}
    assert outcomes & {"kept", "sync", "discarded"}
    eng.stop()


def test_dump_schema_stable():
    """The dump key sets are a contract: top level, step records, and
    timeline dicts.  Extending them is fine — update this test AND bump
    SCHEMA_VERSION when a key is renamed/removed."""
    eng = make_engine()
    eng.generate(prompt_ids=[5, 6, 7], sampling=SamplingParams(
        temperature=0.0, max_new_tokens=3, ignore_eos=True))
    dump = eng.dump_flight("manual")
    assert dump["schema_version"] == SCHEMA_VERSION
    assert {
        "schema_version", "reason", "ts_unix", "t_mono", "last_step_serial",
        "ring", "timelines", "auto_dumps", "engine",
    } <= set(dump)
    assert dump["reason"] == "manual"
    for rec in dump["ring"]:
        assert set(rec) == STEP_RECORD_KEYS
    tl = dump["timelines"]["finished"][0]
    assert {
        "rid", "trace_id", "meta", "queued_t", "admitted_t", "first_token_t",
        "finish_t", "finish_reason", "finish_message", "deadline_t", "ttft_s",
        "e2e_s", "prompt_tokens", "cached_tokens", "output_tokens", "itl",
        "events",
    } == set(tl)
    assert {"count", "mean_s", "p50_s", "p95_s", "max_s"} == set(tl["itl"])
    assert tl["meta"]["temperature"] == 0.0
    json.dumps(dump)  # JSON-able end to end
    eng.stop()


# ---- auto-dump triggers (driven through faults.py) ----


def test_dump_on_quarantine_contains_failing_step_and_culprit():
    """A fault-injected poison decode step auto-dumps; the dump's ring
    contains the failing step (fault flags set) and its timelines identify
    the quarantined request (acceptance criterion, engine-local half)."""
    FAULTS.arm_from_env("engine.decode_step=once")  # the SMG_FAULTS grammar
    eng = make_engine()
    outs: dict = {}
    for rid in ("a", "b"):
        eng.submit([5, 6, 7], SamplingParams(
            temperature=0.0, max_new_tokens=4, ignore_eos=True),
            rid=rid, on_output=_collector(outs, rid))
    _drive(eng, outs, ["a", "b"])
    fl = eng.scheduler.flight
    assert [d["reason"] for d in fl.dumps] == ["quarantine"]
    dump = fl.dumps[0]
    faulted = [r for r in dump["ring"] if "decode" in r["faults"]]
    assert faulted, "dump ring lost the failing step"
    quarantined = [
        t for t in dump["timelines"]["finished"]
        if any(e["kind"] == "quarantine" for e in t["events"])
    ]
    assert len(quarantined) == 1
    assert quarantined[0]["finish_reason"] == "error"
    # the blamed rid really is the one that saw finish_reason=error
    errored = [r for r in outs if outs[r][-1].finish_reason == "error"]
    assert [quarantined[0]["rid"]] == errored
    eng.stop()


def test_health_flip_dump_on_consecutive_failures():
    """Crossing max_consecutive_step_failures dumps reason=health_flip.
    One prefill-quarantine per step keeps the failure streak unbroken (a
    batch condemn resolves in a single step and never reaches the
    threshold)."""
    FAULTS.arm("engine.prefill", mode="always")
    eng = make_engine()
    outs: dict = {}
    for i in range(4):
        eng.submit([5 + i, 6, 7], SamplingParams(
            temperature=0.0, max_new_tokens=8, ignore_eos=True),
            rid=f"r{i}", on_output=_collector(outs, f"r{i}"))
        eng.step()  # each step fails (and quarantines) one prefill
        if not eng.healthy:
            break
    assert not eng.healthy
    reasons = [d["reason"] for d in eng.scheduler.flight.dumps]
    assert "health_flip" in reasons
    FAULTS.clear()
    eng.stop()


def test_dump_on_watchdog_stall():
    """A wedged device fetch (injected hang) makes the watchdog dump the
    black box — lock-free, while the step thread still holds the engine
    lock — and the dump is fetchable via dump_flight at the same moment."""
    eng = make_engine(watchdog_secs=0.3)
    eng.start()
    try:
        eng.generate(prompt_ids=[5, 6, 7], sampling=SamplingParams(
            temperature=0.0, max_new_tokens=4, ignore_eos=True))  # warm
        FAULTS.arm("engine.device_fetch", mode="once", action="hang", delay=2.0)
        outs: dict = {}
        eng.submit([8, 9, 10], SamplingParams(
            temperature=0.0, max_new_tokens=4, ignore_eos=True),
            rid="w", on_output=_collector(outs, "w"))
        deadline = time.monotonic() + 30
        dumped = False
        while time.monotonic() < deadline:
            if any(d["reason"] == "watchdog_stall"
                   for d in eng.scheduler.flight.dumps):
                dumped = True
                # postmortem fetch works mid-stall (no engine lock taken)
                snap = eng.dump_flight("probe")
                assert snap["last_auto_dump"]["reason"] == "watchdog_stall"
                break
            time.sleep(0.02)
        assert dumped, "watchdog stall never produced a flight dump"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if outs.get("w") and outs["w"][-1].finished:
                break
            time.sleep(0.02)
        assert outs["w"][-1].finished
    finally:
        eng.stop()


def test_dump_on_drain():
    eng = make_engine()
    eng.start()
    eng.generate(prompt_ids=[5, 6], sampling=SamplingParams(
        temperature=0.0, max_new_tokens=2, ignore_eos=True))
    eng.stop(drain=True, timeout=5.0)
    assert "drain" in [d["reason"] for d in eng.scheduler.flight.dumps]


def test_failing_dump_degrades_to_log_not_failure():
    """An armed flight.dump fault breaks the dump path; the quarantine it
    was reporting still completes cleanly and the engine keeps serving."""
    FAULTS.arm("flight.dump")
    FAULTS.arm("engine.decode_step", mode="once")
    eng = make_engine()
    outs: dict = {}
    eng.submit([5, 6, 7], SamplingParams(
        temperature=0.0, max_new_tokens=4, ignore_eos=True),
        rid="a", on_output=_collector(outs, "a"))
    _drive(eng, outs, ["a"])
    assert outs["a"][-1].finish_reason == "error"  # quarantine still landed
    assert len(eng.scheduler.flight.dumps) == 0  # dump failed, engine fine
    FAULTS.clear()
    r = eng.generate(prompt_ids=[8, 9], sampling=SamplingParams(
        temperature=0.0, max_new_tokens=2, ignore_eos=True))
    assert len(r.token_ids) == 2
    eng.stop()


def test_auto_dump_rate_limit_is_per_reason():
    fl = FlightRecorder(dump_min_interval_secs=60.0)
    assert fl.auto_dump("quarantine") is True
    assert fl.auto_dump("quarantine") is False  # throttled
    assert fl.auto_dump("drain") is True  # different reason passes
    assert fl.num_dump_suppressed == 1
    assert [d["reason"] for d in fl.dumps] == ["quarantine", "drain"]


def test_recorder_off_engine_still_works():
    eng = make_engine(flight_kw=dict(flight_recorder=False))
    assert eng.scheduler.flight is None
    r = eng.generate(prompt_ids=[5, 6, 7], sampling=SamplingParams(
        temperature=0.0, max_new_tokens=3, ignore_eos=True))
    assert len(r.token_ids) == 3
    assert eng.dump_flight()["error"] == "flight recorder disabled"
    eng.stop()


def test_dump_dir_writes_reason_tagged_files(tmp_path):
    eng = make_engine(flight_kw=dict(flight_dump_dir=str(tmp_path)))
    FAULTS.arm("engine.decode_step", mode="once")
    outs: dict = {}
    eng.submit([5, 6, 7], SamplingParams(
        temperature=0.0, max_new_tokens=4, ignore_eos=True),
        rid="a", on_output=_collector(outs, "a"))
    _drive(eng, outs, ["a"])
    files = list(tmp_path.glob("flight-*-quarantine.json"))
    assert len(files) == 1
    on_disk = json.loads(files[0].read_text())
    assert on_disk["schema_version"] == SCHEMA_VERSION
    assert on_disk["reason"] == "quarantine"
    eng.stop()


# ---- RPC + gateway fetch path (acceptance criterion, end to end) ----


def test_flight_dump_fetchable_end_to_end_over_rpc():
    """SMG_FAULTS=engine.decode_step poisons one decode step; the auto-dump
    is then fetched through the FULL path: gateway HTTP
    GET /debug/flight/{worker} -> GrpcWorkerClient.DumpFlight -> worker
    servicer -> Engine.dump_flight."""
    from smg_tpu.rpc.client import GrpcWorkerClient
    from smg_tpu.rpc.server import serve_worker_async

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=180):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    engine = make_engine()
    engine.start()

    async def _setup():
        server = await serve_worker_async(engine, port=0, host="127.0.0.1")
        client = GrpcWorkerClient(f"127.0.0.1:{server._bound_port}")
        ctx = AppContext(policy="round_robin")
        ctx.tokenizers.register("tiny-flight", MockTokenizer(), default=True)
        ctx.registry.add(Worker(worker_id="w0", client=client,
                                model_id="tiny-flight"))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return server, client, tc

    server, client, tc = run(_setup())
    try:
        # warm path (compiles), then poison exactly one decode step
        async def warm():
            req = WorkerGenerateRequest(
                rid="warm", input_ids=[5, 6, 7],
                sampling=SamplingParams(temperature=0.0, max_new_tokens=2,
                                        ignore_eos=True))
            async for _ in client.generate(req):
                pass
        run(warm())
        assert FAULTS.arm_from_env("engine.decode_step=once") == 1

        async def poisoned():
            chunks = []
            req = WorkerGenerateRequest(
                rid="poison-me", input_ids=[8, 9, 10],
                sampling=SamplingParams(temperature=0.0, max_new_tokens=4,
                                        ignore_eos=True))
            async for c in client.generate(req):
                chunks.append(c)
            return chunks
        chunks = run(poisoned())
        assert chunks[-1].finish_reason == "error"

        async def fetch():
            r = await tc.get("/debug/flight/w0")
            return r.status, await r.json()
        status, body = run(fetch())
        assert status == 200 and body["worker_id"] == "w0"
        dump = body["dump"]
        assert dump["schema_version"] == SCHEMA_VERSION
        auto = dump["last_auto_dump"]
        assert auto["reason"] == "quarantine"
        assert any("decode" in r["faults"] for r in auto["ring"])
        quarantined = [
            tl for tl in auto["timelines"]["finished"]
            if any(e["kind"] == "quarantine" for e in tl["events"])
        ]
        assert [tl["rid"] for tl in quarantined] == ["poison-me"]

        async def fetch_missing():
            r = await tc.get("/debug/flight/ghost")
            return r.status
        assert run(fetch_missing()) == 404
    finally:
        run(tc.close())
        run(client.close())
        run(server.stop(grace=None))
        loop.call_soon_threadsafe(loop.stop)
        engine.stop()


def test_traceparent_joins_worker_timeline_over_grpc():
    """The gateway's ambient span rides gRPC metadata; the engine-side
    flight timeline records the SAME trace id (satellite: no fresh trace
    root per worker hop)."""
    from smg_tpu.gateway.tracing import OtelTracer, current_span, current_tracer
    from smg_tpu.rpc.client import GrpcWorkerClient
    from smg_tpu.rpc.server import serve_worker_async

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=120):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    engine = make_engine()
    engine.start()
    worker_tracer = OtelTracer("http://collector.invalid")  # never flushed

    async def _setup():
        server = await serve_worker_async(
            engine, port=0, host="127.0.0.1", tracer=worker_tracer
        )
        return server, GrpcWorkerClient(f"127.0.0.1:{server._bound_port}")

    server, client = run(_setup())
    gateway_tracer = OtelTracer("http://collector.invalid")
    span = gateway_tracer.start_span("POST /v1/chat/completions")
    try:
        async def go():
            tok_s = current_span.set(span)
            tok_t = current_tracer.set(gateway_tracer)
            try:
                req = WorkerGenerateRequest(
                    rid="traced", input_ids=[5, 6, 7],
                    sampling=SamplingParams(temperature=0.0, max_new_tokens=2,
                                            ignore_eos=True))
                async for _ in client.generate(req):
                    pass
            finally:
                current_span.reset(tok_s)
                current_tracer.reset(tok_t)
        run(go())
        tl = {
            t["rid"]: t
            for t in engine.dump_flight()["timelines"]["finished"]
        }["traced"]
        assert tl["trace_id"] == span.trace_id
        # worker-side span joined the SAME trace rather than rooting a new one
        worker_spans = [s for s in worker_tracer._buffer
                        if s.name == "worker.generate"]
        assert worker_spans and worker_spans[0].trace_id == span.trace_id
        assert worker_spans[0].parent_span_id == span.span_id
    finally:
        run(client.close())
        run(server.stop(grace=None))
        loop.call_soon_threadsafe(loop.stop)
        engine.stop()


# ---- TTFT retry attribution + SLO tracker (gateway side) ----


class _SharedQueueFullOnce:
    """First generate() across the pool hits queue-full after a delay, so
    whichever worker the policy picks first forces a failover."""

    def __init__(self, delay: float):
        self.delay = delay
        self.lock = threading.Lock()
        self.tripped = False

    def trip(self) -> bool:
        with self.lock:
            if not self.tripped:
                self.tripped = True
                return True
            return False


class _StubWorkerClient(WorkerClient):
    def __init__(self, shared: _SharedQueueFullOnce):
        self.shared = shared

    async def generate(self, req):
        if self.shared.trip():
            await asyncio.sleep(self.shared.delay)
            raise WorkerQueueFullError("induced backpressure")
        yield WorkerStreamChunk(
            rid=req.rid, token_ids=[1], finished=False, prompt_tokens=3,
            output_tokens=1,
        )
        yield WorkerStreamChunk(
            rid=req.rid, token_ids=[2], finished=True, finish_reason="stop",
            prompt_tokens=3, output_tokens=2,
        )

    async def abort(self, rid):
        return True

    async def health(self):
        return True

    async def get_loads(self):
        return {"num_waiting": 0, "num_running": 0, "queued_tokens": 0}


def _hist_sample(metrics_registry, name, suffix, labels):
    for fam in metrics_registry.collect():
        for s in fam.samples:
            if s.name == name + suffix and all(
                s.labels.get(k) == v for k, v in labels.items()
            ):
                return s.value
    return None


def test_ttft_measured_from_first_dispatch_across_queue_full_failover():
    """Satellite: after a WorkerQueueFullError failover, TTFT must span BOTH
    dispatches — the induced 80ms first-worker delay has to show up in
    smg_time_to_first_token_seconds, and exactly one sample is recorded."""
    from smg_tpu.gateway.router import Router
    from smg_tpu.policies import PolicyRegistry, RequestContext
    from smg_tpu.tokenizer.registry import TokenizerRegistry

    shared = _SharedQueueFullOnce(delay=0.08)
    registry = WorkerRegistry()
    registry.add(Worker(worker_id="wa", client=_StubWorkerClient(shared),
                        model_id="m"))
    registry.add(Worker(worker_id="wb", client=_StubWorkerClient(shared),
                        model_id="m"))
    metrics = Metrics()
    router = Router(registry, PolicyRegistry(default="round_robin"),
                    TokenizerRegistry(), metrics=metrics)

    async def go():
        evs = []
        ctx = RequestContext(model_id="m", request_id="t1")
        async for ev in router._execute(
            ctx, [1, 2, 3], SamplingParams(max_new_tokens=4), "t1", None
        ):
            evs.append(ev)
        return evs

    evs = asyncio.run(go())
    assert evs[-1].finished and evs[-1].finish_reason == "stop"
    count = _hist_sample(metrics.registry, "smg_time_to_first_token_seconds",
                         "_count", {"route": "unknown"})
    total = _hist_sample(metrics.registry, "smg_time_to_first_token_seconds",
                         "_sum", {"route": "unknown"})
    assert count == 1.0, "TTFT must be observed exactly once per request"
    assert total >= 0.08, (
        f"TTFT {total}s lost the queue-full failover latency"
    )
    assert shared.tripped
    # the SLO record agrees with the metric: one request, ttft >= failover
    rec = metrics.slo.summary()["recent"][-1]
    assert rec["rid"] == "t1" and rec["ttft_s"] >= 0.08
    assert rec["reason"] == "stop" and rec["output_tokens"] == 2


def test_slo_tracker_deadline_and_goodput():
    m = Metrics()
    # deadline met: fast clean finish
    r1 = m.slo.begin("ok", route="/v1/completions", deadline_secs=5.0)
    r1.first_token(10, 2)
    r1.tokens(3)
    r1.tokens(2)
    r1.finish("stop")
    # deadline missed: engine timeout finish
    r2 = m.slo.begin("late", route="/v1/completions", deadline_secs=5.0)
    r2.first_token(10, 0)
    r2.tokens(1)
    r2.finish("timeout")
    # no deadline: clean finish counts toward goodput, not deadline outcomes
    r3 = m.slo.begin("free", route="/v1/chat/completions")
    r3.first_token(4, 0)
    r3.tokens(4)
    r3.finish("stop")
    # terminal transitions are idempotent
    r3.fail("error")

    s = m.slo.summary()
    assert s["window_requests"] == 3
    assert s["deadline"] == {"with_deadline": 2, "met": 1, "missed": 1}
    assert s["goodput"]["tokens"] == 5 + 4  # ok(5) + free(4), late excluded
    assert s["finish_reasons"] == {"stop": 2, "timeout": 1}
    assert s["ttft"]["p95_s"] >= 0.0 and s["recent"][-1]["rid"] == "free"
    met = _hist_sample(m.registry, "smg_request_deadline_outcomes_total", "",
                       {"outcome": "met"})
    missed = _hist_sample(m.registry, "smg_request_deadline_outcomes_total",
                          "", {"outcome": "missed"})
    good = _hist_sample(m.registry, "smg_goodput_tokens_total", "", {})
    assert (met, missed, good) == (1.0, 1.0, 9.0)


def test_debug_slo_endpoint_over_gateway():
    """/debug/slo reflects requests served through the real dispatch path
    (in-proc engine worker) including ITL observations."""
    eng = make_engine()
    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("tiny-flight", MockTokenizer(), default=True)

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=180):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    async def _setup():
        ctx.registry.add(Worker(worker_id="w0", client=InProcWorkerClient(eng),
                                model_id="tiny-flight"))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    tc = run(_setup())
    try:
        async def go():
            r = await tc.post("/v1/chat/completions", json={
                "model": "tiny-flight",
                "messages": [{"role": "user", "content": "w5 w6 w7"}],
                "max_tokens": 6, "temperature": 0, "ignore_eos": True,
            })
            assert r.status == 200
            r2 = await tc.get("/debug/slo")
            return await r2.json()

        s = run(go())
        assert s["window_requests"] == 1
        rec = s["recent"][-1]
        assert rec["route"] == "/v1/chat/completions"
        assert rec["reason"] == "length" and rec["output_tokens"] == 6
        assert rec["ttft_s"] > 0 and rec["deadline_met"] is True
        # engine-side timeline for the same request exists with matching rid
        dump = eng.dump_flight()
        assert any(tl["rid"] == rec["rid"]
                   for tl in dump["timelines"]["finished"])
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)
        eng.stop()
