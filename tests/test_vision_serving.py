"""Multimodal SERVING path e2e (VERDICT r3 #1): image content parts through
the HTTP gateway -> encode leg -> mm splice -> generation, over real gRPC.

Reference parity: the EncodeStage + encoder servicer + prefill splice
(``model_gateway/src/routers/grpc/common/stages/encode.rs:1-40``,
``grpc_servicer/smg_grpc_servicer/tokenspeed/encoder_servicer.py``)."""

import asyncio
import base64
import io
import json
import threading

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.models.config import tiny_vlm_config
from smg_tpu.multimodal.ingest import (
    ImageIngestError,
    expand_image_placeholders,
    extract_image_parts,
    flatten_content,
)
from smg_tpu.multimodal.processor import processor_for_worker
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


def _vlm_engine() -> Engine:
    cfg = EngineConfig(
        model=tiny_vlm_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=256, max_prefill_tokens=64,
            prefill_token_buckets=(32, 64), decode_batch_buckets=(2, 4),
        ),
        dtype="float32",
        model_id="tiny-vlm",
    )
    return Engine(cfg, tokenizer=MockTokenizer())


def _png_data_uri(rng, h=24, w=16) -> str:
    from PIL import Image

    arr = rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr, "RGB").save(buf, format="PNG")
    return "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()


# ---- ingest unit tests ----


def test_extract_and_flatten_content():
    messages = [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": [
            {"type": "text", "text": "w5"},
            {"type": "image_url", "image_url": {"url": "data:,x"}},
            {"type": "text", "text": "w6"},
        ]},
    ]
    parts = extract_image_parts(messages)
    assert len(parts) == 1
    flat = flatten_content(messages, "w500")
    assert flat[0]["content"] == "be brief"
    assert flat[1]["content"] == "w5 w500 w6"
    # original untouched
    assert isinstance(messages[1]["content"], list)


def test_expand_image_placeholders():
    ids, pos = expand_image_placeholders([1, 500, 2, 500, 3], 500, [2, 3])
    assert ids == [1, 500, 500, 2, 500, 500, 500, 3]
    assert pos == [1, 2, 4, 5, 6]
    with pytest.raises(ImageIngestError):
        expand_image_placeholders([1, 500, 2], 500, [2, 3])  # count mismatch


def test_fetch_image_data_uri():
    from smg_tpu.multimodal.ingest import fetch_image

    async def go():
        rng = np.random.default_rng(0)
        uri = _png_data_uri(rng, 8, 6)
        arr = await fetch_image({"type": "image_url", "image_url": {"url": uri}})
        assert arr.shape == (8, 6, 3) and arr.dtype == np.uint8
        # Anthropic-style base64 source block
        raw = base64.b64decode(uri.split(",", 1)[1])
        arr2 = await fetch_image({
            "type": "image", "source": {"type": "base64",
                                        "data": base64.b64encode(raw).decode()},
        })
        np.testing.assert_array_equal(arr, arr2)
        with pytest.raises(ImageIngestError):
            await fetch_image({"type": "image_url", "image_url": {"url": "!!!"}})

    asyncio.run(go())


def test_mm_proto_roundtrip():
    from smg_tpu.rpc.convert import mm_embeds_from_proto, mm_embeds_to_proto

    rng = np.random.default_rng(1)
    embeds = rng.standard_normal((5, 16)).astype(np.float32)
    positions = np.asarray([3, 4, 5, 6, 7])
    msg = mm_embeds_to_proto((embeds, positions))
    back = mm_embeds_from_proto(msg)
    np.testing.assert_array_equal(back[0], embeds)
    np.testing.assert_array_equal(back[1], positions)
    assert mm_embeds_to_proto(None) is None
    assert mm_embeds_from_proto(None) is None


# ---- e2e: HTTP gateway -> gRPC worker -> encode + mm generate ----


@pytest.fixture(scope="module")
def vlm_stack():
    """Gateway (aiohttp TestClient) over a real gRPC VLM worker."""
    from smg_tpu.gateway.server import AppContext, build_app
    from smg_tpu.gateway.workers import Worker
    from smg_tpu.rpc.client import GrpcWorkerClient
    from smg_tpu.rpc.server import serve_worker_async

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=300):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    engine = _vlm_engine()
    engine.start()
    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("tiny-vlm", MockTokenizer(), default=True)

    async def _setup():
        server = await serve_worker_async(engine, port=0, host="127.0.0.1")
        client = GrpcWorkerClient(f"127.0.0.1:{server._bound_port}")
        ctx.registry.add(Worker(worker_id="vlm0", client=client, model_id="tiny-vlm"))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return server, client, tc

    server, client, tc = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.engine, h.tc, h.ctx = run, engine, tc, ctx
    yield h
    run(tc.close())
    run(client.close())
    run(server.stop(grace=None))
    loop.call_soon_threadsafe(loop.stop)
    engine.stop()


def _expected_ids(engine, messages, uri_arrays, max_new=8):
    """Mirror the gateway pipeline engine-side for a parity target."""
    tok = MockTokenizer()
    info_patch = engine.config.model.vision.patch_size
    info_merge = engine.config.model.vision.merge_size
    pad = engine.config.model.image_token_id
    proc = processor_for_worker("tiny-vlm", patch_size=info_patch,
                                merge_size=info_merge)
    embeds, counts = [], []
    for arr in uri_arrays:
        p = proc.process(arr)
        e = engine.encode_image(np.asarray(p.pixel_values, np.float32), p.grid)
        assert e.shape[0] == p.num_placeholder_tokens
        embeds.append(e)
        counts.append(p.num_placeholder_tokens)
    flat = flatten_content(messages, tok.decode([pad]))
    prompt = tok.apply_chat_template(flat, add_generation_prompt=True)
    ids = tok.encode(prompt)
    ids, positions = expand_image_placeholders(ids, pad, counts)
    # direct submit with mm (engine.generate has no mm param)
    done = threading.Event()
    acc = []

    def cb(out):
        acc.extend(out.new_token_ids)
        if out.finished:
            done.set()

    engine.submit(ids, SamplingParams(temperature=0.0, max_new_tokens=max_new,
                                      ignore_eos=True),
                  rid="parity-target", on_output=cb,
                  mm_embeds=(np.concatenate(embeds), positions))
    assert done.wait(timeout=300)
    return list(acc)


def test_image_chat_e2e_over_grpc(vlm_stack):
    """An image chat request completes through the HTTP gateway against a
    VLM worker over real gRPC, and matches the engine-direct mm path
    token-for-token (the VERDICT r3 'done' condition)."""
    h = vlm_stack
    rng = np.random.default_rng(7)
    uri = _png_data_uri(rng)
    messages = [{"role": "user", "content": [
        {"type": "text", "text": "w5"},
        {"type": "image_url", "image_url": {"url": uri}},
        {"type": "text", "text": "w6"},
    ]}]

    async def go():
        r = await h.tc.post("/v1/chat/completions", json={
            "model": "tiny-vlm", "messages": messages,
            "max_tokens": 8, "temperature": 0, "ignore_eos": True,
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    text = body["choices"][0]["message"]["content"]
    assert text

    # parity: identical pipeline engine-side
    from smg_tpu.multimodal.ingest import fetch_image

    arr = h.run(fetch_image(messages[0]["content"][1]))
    want_ids = _expected_ids(h.engine, messages, [arr])
    want_text = MockTokenizer().decode(want_ids)
    assert text == want_text
    # placeholder expansion grew the prompt beyond the raw words
    assert body["usage"]["prompt_tokens"] > 10


def test_image_chat_streaming(vlm_stack):
    h = vlm_stack
    rng = np.random.default_rng(9)
    uri = _png_data_uri(rng, 16, 16)

    async def go():
        r = await h.tc.post("/v1/chat/completions", json={
            "model": "tiny-vlm",
            "messages": [{"role": "user", "content": [
                {"type": "image_url", "image_url": {"url": uri}},
                {"type": "text", "text": "w9"},
            ]}],
            "max_tokens": 6, "temperature": 0, "ignore_eos": True,
            "stream": True,
        })
        return r.status, await r.text()

    status, raw = h.run(go())
    assert status == 200
    frames = [l[6:] for l in raw.splitlines() if l.startswith("data: ")]
    assert frames[-1] == "[DONE]"
    parsed = [json.loads(f) for f in frames if f != "[DONE]"]
    out = "".join(
        p["choices"][0]["delta"].get("content") or "" for p in parsed if p["choices"]
    )
    assert out.strip()


def test_anthropic_image_message_e2e(vlm_stack):
    """Anthropic Messages surface: base64 image source blocks reach the
    same encode leg (reference: multi-surface mm parity)."""
    h = vlm_stack
    rng = np.random.default_rng(11)
    uri = _png_data_uri(rng, 16, 24)
    b64 = uri.split(",", 1)[1]

    async def go():
        r = await h.tc.post("/v1/messages", json={
            "model": "tiny-vlm", "max_tokens": 6,
            "messages": [{"role": "user", "content": [
                {"type": "image", "source": {
                    "type": "base64", "media_type": "image/png", "data": b64}},
                {"type": "text", "text": "w5"},
            ]}],
            "temperature": 0,
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    blocks = body.get("content") or []
    assert any(b.get("type") == "text" and b.get("text") for b in blocks), body


def test_image_chat_bad_payload_400(vlm_stack):
    h = vlm_stack

    async def go():
        r = await h.tc.post("/v1/chat/completions", json={
            "model": "tiny-vlm",
            "messages": [{"role": "user", "content": [
                {"type": "image_url", "image_url": {"url": "data:image/png;base64,%%%"}},
            ]}],
            "max_tokens": 4,
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 400
    assert "base64" in json.dumps(body)


def test_text_only_model_rejects_images():
    """A text-only deployment answers 400 (not 500) to image content."""
    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.gateway.server import AppContext, build_app
    from smg_tpu.gateway.worker_client import InProcWorkerClient
    from smg_tpu.gateway.workers import Worker
    from smg_tpu.models.config import tiny_test_config

    eng = Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=2, max_seq_len=128, max_prefill_tokens=32,
            prefill_token_buckets=(32,), decode_batch_buckets=(2,),
        ),
        dtype="float32", model_id="text-only",
    ), tokenizer=MockTokenizer())
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=120):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("text-only", MockTokenizer(), default=True)

    async def _setup():
        ctx.registry.add(Worker(worker_id="w0", client=InProcWorkerClient(eng),
                                model_id="text-only"))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    tc = run(_setup())
    try:
        async def go():
            r = await tc.post("/v1/chat/completions", json={
                "model": "text-only",
                "messages": [{"role": "user", "content": [
                    {"type": "text", "text": "w5"},
                    {"type": "image_url", "image_url": {"url": "data:,x"}},
                ]}],
                "max_tokens": 4,
            })
            return r.status, await r.json()

        status, body = run(go())
        assert status == 400
        assert "image" in json.dumps(body).lower()
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)
        eng.stop()


# ---- vision weight loading (HF checkpoint -> tower pytree) ----


def _fake_vision_checkpoint(tmp_path, vcfg, out_hidden, conv3d=False):
    """Random Qwen2-VL-style ``visual.*`` safetensors checkpoint."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(0)
    H, I = vcfg.hidden_size, vcfg.intermediate_size
    m2 = vcfg.merge_size**2
    ps, C = vcfg.patch_size, vcfg.in_channels

    def r(*shape):
        return (rng.standard_normal(shape) * 0.05).astype(np.float32)

    tensors = {}
    if conv3d:
        tensors["visual.patch_embed.proj.weight"] = r(H, C, 2, ps, ps)
    else:
        tensors["visual.patch_embed.proj.weight"] = r(H, C, ps, ps)
    for i in range(vcfg.num_layers):
        p = f"visual.blocks.{i}"
        tensors[f"{p}.norm1.weight"] = r(H) + 1.0
        tensors[f"{p}.norm1.bias"] = r(H)
        tensors[f"{p}.attn.qkv.weight"] = r(3 * H, H)
        tensors[f"{p}.attn.qkv.bias"] = r(3 * H)
        tensors[f"{p}.attn.proj.weight"] = r(H, H)
        tensors[f"{p}.attn.proj.bias"] = r(H)
        tensors[f"{p}.norm2.weight"] = r(H) + 1.0
        tensors[f"{p}.norm2.bias"] = r(H)
        tensors[f"{p}.mlp.fc1.weight"] = r(I, H)
        tensors[f"{p}.mlp.fc1.bias"] = r(I)
        tensors[f"{p}.mlp.fc2.weight"] = r(H, I)
        tensors[f"{p}.mlp.fc2.bias"] = r(H)
    tensors["visual.merger.ln_q.weight"] = r(H) + 1.0
    tensors["visual.merger.ln_q.bias"] = r(H)
    tensors["visual.merger.mlp.0.weight"] = r(H * m2, H * m2)
    tensors["visual.merger.mlp.0.bias"] = r(H * m2)
    tensors["visual.merger.mlp.2.weight"] = r(out_hidden, H * m2)
    tensors["visual.merger.mlp.2.bias"] = r(out_hidden)
    save_file(tensors, str(tmp_path / "model.safetensors"))
    return tensors


@pytest.mark.parametrize("conv3d", [False, True])
def test_load_vision_params_conv_order(tmp_path, conv3d):
    """The conv->matrix flatten must agree with torch's conv semantics in
    patchify's (ps, ps, C) element order — checked against F.conv2d/conv3d
    as an independent oracle."""
    import torch
    import torch.nn.functional as F

    from smg_tpu.models.weights import load_vision_params
    from smg_tpu.multimodal.image import patchify

    cfg = _vlm_engine().config  # tiny vlm (engine unused further)
    vcfg = cfg.model.vision
    tensors = _fake_vision_checkpoint(
        tmp_path, vcfg, cfg.model.hidden_size, conv3d=conv3d
    )
    import dataclasses

    ecfg = dataclasses.replace(cfg, model_path=str(tmp_path))
    params = load_vision_params(ecfg)
    assert params["patch_embed"].shape == (vcfg.patch_dim, vcfg.hidden_size)

    ps, C = vcfg.patch_size, vcfg.in_channels
    rng = np.random.default_rng(3)
    img = rng.standard_normal((2 * ps, 3 * ps, C)).astype(np.float32)
    patches, grid = patchify(img, ps)
    ours = np.asarray(patches, np.float32) @ np.asarray(params["patch_embed"])

    w = torch.from_numpy(tensors["visual.patch_embed.proj.weight"])
    ti = torch.from_numpy(img).permute(2, 0, 1)[None]  # [1, C, H, W]
    if conv3d:
        ti = ti.unsqueeze(2).repeat(1, 1, 2, 1, 1)  # duplicated frame
        out = F.conv3d(ti, w, stride=(2, ps, ps))[0, :, 0]  # [H, gh, gw]
    else:
        out = F.conv2d(ti, w, stride=ps)[0]  # [H, gh, gw]
    theirs = out.permute(1, 2, 0).reshape(-1, vcfg.hidden_size).numpy()
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)


def test_engine_uses_loaded_vision_params(tmp_path):
    """Engine(vision_params=...) serves the loaded tower, not random init."""
    import dataclasses

    from smg_tpu.models.weights import load_vision_params

    base = _vlm_engine()
    try:
        vcfg = base.config.model.vision
        _fake_vision_checkpoint(tmp_path, vcfg, base.config.model.hidden_size)
        ecfg = dataclasses.replace(base.config, model_path=str(tmp_path))
        vp = load_vision_params(ecfg)
        eng = Engine(base.config, tokenizer=MockTokenizer(), vision_params=vp)
        try:
            gh = gw = 4
            rng = np.random.default_rng(5)
            pixels = rng.standard_normal((gh * gw, vcfg.patch_dim)).astype(np.float32)
            out = eng.encode_image(pixels, (gh, gw))
            from smg_tpu.models.vit import forward_vision

            want = np.asarray(
                forward_vision(vp, vcfg, pixels, (gh, gw)), np.float32
            )
            np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)
            # differs from the random-init tower
            rand = base.encode_image(pixels, (gh, gw))
            assert not np.allclose(out, rand)
        finally:
            eng.stop()
    finally:
        base.stop()


def test_encode_shm_transport_parity(vlm_stack):
    """Same-host shm pixel transport (reference mm transport ladder,
    main.rs:319-328): forced-shm encode matches inline bit-for-bit and the
    segment is unlinked afterwards."""
    h = vlm_stack
    import glob

    from smg_tpu.rpc.client import GrpcWorkerClient

    vcfg = h.engine.config.model.vision
    rng = np.random.default_rng(13)
    gh, gw = 4, 8
    pixels = rng.standard_normal((gh * gw, vcfg.patch_dim)).astype(np.float32)

    client = next(
        w.client for w in h.ctx.registry.list()
        if isinstance(w.client, GrpcWorkerClient)
    )
    before = set(glob.glob("/dev/shm/*"))

    async def go(mode, min_bytes=0):
        old_t, old_m = client.mm_transport, client.mm_shm_min_bytes
        client.mm_transport, client.mm_shm_min_bytes = mode, min_bytes
        try:
            return await client.encode_image(pixels, (gh, gw))
        finally:
            client.mm_transport, client.mm_shm_min_bytes = old_t, old_m

    inline = h.run(go("inline"))
    shm = h.run(go("shm"))
    np.testing.assert_array_equal(inline, shm)
    # auto below threshold -> inline path still works
    auto_small = h.run(go("auto", min_bytes=1 << 30))
    np.testing.assert_array_equal(inline, auto_small)
    # auto above threshold on loopback -> shm path
    auto_big = h.run(go("auto", min_bytes=1))
    np.testing.assert_array_equal(inline, auto_big)
    # no leaked segments
    after = set(glob.glob("/dev/shm/*"))
    assert after <= before | set()
