"""Multi-model RouterManager (IGW) e2e — one gateway, several models, each
with its own router/policy (reference: router_manager.rs:1-5, factory.rs;
VERDICT r3 next-round #3)."""

import asyncio
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.gateway.router import RouterConfig
from smg_tpu.gateway.server import AppContext, build_app
from smg_tpu.gateway.worker_client import InProcWorkerClient
from smg_tpu.gateway.workers import Worker
from smg_tpu.models.config import tiny_test_config
from smg_tpu.tokenizer import MockTokenizer


def make_engine(model_id: str) -> Engine:
    return Engine(
        EngineConfig(
            model=tiny_test_config(),
            cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                              dtype="float32"),
            scheduler=SchedulerConfig(
                max_batch_size=4, max_seq_len=128, max_prefill_tokens=32,
                prefill_token_buckets=(16, 32), decode_batch_buckets=(4,),
            ),
            dtype="float32",
            model_id=model_id,
        ),
        tokenizer=MockTokenizer(),
    )


@pytest.fixture(scope="module")
def igw():
    """Two models, one gateway: model-a (one worker), model-b (two workers)."""
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=300):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    engines = [make_engine("model-a"), make_engine("model-b"), make_engine("model-b")]
    ctx = AppContext(
        policy="round_robin",
        router_config=RouterConfig(default_max_tokens=4),
    )
    ctx.tokenizers.register("model-a", MockTokenizer(), default=True)
    ctx.tokenizers.register("model-b", MockTokenizer())
    workers = [
        Worker(worker_id="a0", client=InProcWorkerClient(engines[0]), model_id="model-a"),
        Worker(worker_id="b0", client=InProcWorkerClient(engines[1]), model_id="model-b"),
        Worker(worker_id="b1", client=InProcWorkerClient(engines[2]), model_id="model-b"),
    ]

    async def _setup():
        for w in workers:
            ctx.registry.add(w)
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    tc = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.ctx, h.tc, h.workers = run, ctx, tc, {w.worker_id: w for w in workers}
    yield h
    run(tc.close())
    loop.call_soon_threadsafe(loop.stop)
    for e in engines:
        e.stop()


def _chat(h, model, **kw):
    async def go():
        r = await h.tc.post("/v1/chat/completions", json={
            "model": model,
            "messages": [{"role": "user", "content": "w5 w6"}],
            "temperature": 0, "ignore_eos": True, **kw,
        })
        return r.status, await r.json()

    return h.run(go())


def test_model_keyed_dispatch(igw):
    """Requests land only on the named model's workers."""
    h = igw
    for w in h.workers.values():
        w.total_requests = 0
    status, _ = _chat(h, "model-a", max_tokens=3)
    assert status == 200
    status, _ = _chat(h, "model-b", max_tokens=3)
    assert status == 200
    status, _ = _chat(h, "model-b", max_tokens=3)
    assert status == 200
    assert h.workers["a0"].total_requests == 1
    # round_robin spread over model-b's two workers
    assert h.workers["b0"].total_requests + h.workers["b1"].total_requests == 2
    assert h.workers["b0"].total_requests == 1


def test_models_aggregation(igw):
    h = igw

    async def go():
        r = await h.tc.get("/v1/models")
        return await r.json()

    body = h.run(go())
    ids = {m["id"] for m in body["data"]}
    assert {"model-a", "model-b"} <= ids


def test_per_model_router_config(igw):
    """POST /models/{id}/router gives model-b a dedicated router whose
    default_max_tokens differs from the shared default; model-a unaffected."""
    h = igw

    async def set_cfg():
        r = await h.tc.post("/models/model-b/router", json={
            "policy": "random",
            "config": {"default_max_tokens": 2},
        })
        return r.status, await r.json()

    status, desc = h.run(set_cfg())
    assert status == 200
    assert desc["dedicated_router"] is True
    assert desc["policy"] == "random"
    assert desc["config"]["default_max_tokens"] == 2
    assert set(desc["workers"]) == {"b0", "b1"}

    # no max_tokens in the request -> the per-model default applies
    status, body = _chat(h, "model-b")
    assert status == 200
    assert body["usage"]["completion_tokens"] == 2
    status, body = _chat(h, "model-a")
    assert status == 200
    assert body["usage"]["completion_tokens"] == 4  # shared default

    # listing shows both models; reset restores the default router
    async def listing():
        r = await h.tc.get("/routers")
        return await r.json()

    all_desc = h.run(listing())
    by_model = {m["model_id"]: m for m in all_desc["models"]}
    assert by_model["model-b"]["dedicated_router"] is True
    assert by_model["model-a"]["dedicated_router"] is False

    async def reset():
        r = await h.tc.delete("/models/model-b/router")
        return await r.json()

    assert h.run(reset())["reset"] is True
    status, body = _chat(h, "model-b")
    assert status == 200
    assert body["usage"]["completion_tokens"] == 4


def test_unknown_config_field_400(igw):
    h = igw

    async def go():
        r = await h.tc.post("/models/model-a/router", json={
            "config": {"no_such_knob": 1},
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 400
    assert "no_such_knob" in str(body)


def test_unknown_model_still_routes_default(igw):
    """A model id with no workers falls back to the default router, which
    404s/503s sensibly rather than crashing (single-model deployments ignore
    the name — here candidates exist, so it serves)."""
    h = igw
    status, _ = _chat(h, "ghost-model", max_tokens=2)
    # ghost model: candidate filter falls back to all workers (single-model
    # semantics); the request serves — parity with pre-IGW behavior
    assert status == 200
