"""Mesh partition detector (reference: crates/mesh/src/partition.rs) and
the PositionalIndexer jump-search (VERDICT r3 weak #10)."""

import time

import numpy as np
import pytest

from smg_tpu.kv_index.positional import PositionalIndexer, chain_hash
from smg_tpu.mesh import GossipConfig, GossipNode, PartitionConfig, PartitionState
from smg_tpu.mesh.gossip import Member
from smg_tpu.protocols.events import BlockStored, KvEventBatch


def _store(idx, worker, token_ids, ps=4):
    hashes, parent = [], 0
    for i in range(len(token_ids) // ps):
        parent = chain_hash(parent, tuple(token_ids[i * ps:(i + 1) * ps]))
        hashes.append(parent)
    idx.apply_batch(worker, KvEventBatch(
        sequence_number=1,
        events=[BlockStored(block_hashes=hashes, token_ids=token_ids,
                            parent_block_hash=None, block_size=ps)],
    ))


def test_jump_search_exact_depths():
    idx = PositionalIndexer(page_size=4)
    base = list(range(100, 164))  # 16 pages
    _store(idx, "deep", base)                 # all 16 pages
    _store(idx, "mid", base[:24])             # 6 pages
    _store(idx, "shallow", base[:4])          # 1 page
    _store(idx, "other", list(range(500, 540)))  # unrelated

    got = idx.match(base)
    assert got == {"deep": 64, "mid": 24, "shallow": 4}
    # partial query caps the depths
    got = idx.match(base[:26])  # 6 full pages
    assert got == {"deep": 24, "mid": 24, "shallow": 4}
    # no-match query is cheap and empty
    assert idx.match(list(range(900, 964))) == {}
    # sub-page query
    assert idx.match(base[:3]) == {}


def test_jump_search_lazy_hashing():
    """A shallow match must not hash the whole prompt (the lazy-chain
    contract): depth-1-only index over a 1000-page query probes O(1) pages."""
    import smg_tpu.kv_index.positional as mod

    idx = PositionalIndexer(page_size=4)
    base = list(range(100, 104)) + [7] * 3996  # 1000 pages
    _store(idx, "w", base[:4])
    calls = {"n": 0}
    orig = mod.chain_hash

    def counting(parent, tokens):
        calls["n"] += 1
        return orig(parent, tokens)

    mod.chain_hash = counting
    try:
        got = idx.match(base)
    finally:
        mod.chain_hash = orig
    assert got == {"w": 4}
    assert calls["n"] <= 4  # gallop stops immediately; no full-chain hash


def test_partition_detector_states():
    node = GossipNode(GossipConfig(node_id="me"),
                      partition_config=PartitionConfig(
                          unreachable_timeout=1.0, min_cluster_size=3,
                          quorum_threshold=2))
    det = node.partition
    now = time.monotonic()
    node.members = {
        "a": Member(node_id="a", addr="x:1", last_seen=now),
        "b": Member(node_id="b", addr="x:2", last_seen=now),
    }
    assert det.detect(node) is PartitionState.NORMAL
    assert node.has_quorum

    # one peer goes quiet past the timeout: partitioned, but self+a = quorum
    node.members["b"].last_seen = now - 10
    assert det.detect(node) is PartitionState.PARTITIONED_WITH_QUORUM
    assert node.has_quorum

    # both quiet: minority island, no quorum -> fence writes
    node.members["a"].last_seen = now - 10
    assert det.detect(node) is PartitionState.PARTITIONED_WITHOUT_QUORUM
    assert not node.has_quorum
    d = det.describe()
    assert d["state"] == "partitioned_without_quorum"
    assert d["transitions"] == 2

    # recovery
    node.members["a"].last_seen = time.monotonic()
    node.members["b"].last_seen = time.monotonic()
    assert det.detect(node) is PartitionState.NORMAL


def test_partition_small_cluster_never_partitions():
    node = GossipNode(GossipConfig(node_id="me"),
                      partition_config=PartitionConfig(min_cluster_size=3))
    node.members = {"a": Member(node_id="a", addr="x:1",
                                last_seen=time.monotonic() - 999)}
    # 2-node cluster below min_cluster_size: always NORMAL
    assert node.partition.detect(node) is PartitionState.NORMAL


def _make_certs(tmp_path, ca_name="mesh-ca"):
    """Self-signed CA + a node cert signed by it (openssl CLI)."""
    import shutil
    import subprocess

    if shutil.which("openssl") is None:
        pytest.skip("openssl CLI unavailable")

    def run(*args):
        subprocess.run(args, check=True, capture_output=True, cwd=tmp_path)

    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", "ca.key", "-out", "ca.crt", "-days", "1",
        "-subj", f"/CN={ca_name}")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", "node.key", "-out", "node.csr", "-subj", "/CN=mesh-node")
    run("openssl", "x509", "-req", "-in", "node.csr", "-CA", "ca.crt",
        "-CAkey", "ca.key", "-CAcreateserial", "-out", "node.crt",
        "-days", "1")
    return (str(tmp_path / "node.crt"), str(tmp_path / "node.key"),
            str(tmp_path / "ca.crt"))


def test_mesh_mtls_gossip(tmp_path):
    """Two nodes gossip over mutual TLS; a plaintext dial and a
    foreign-CA client are both rejected (reference: mesh transport
    security)."""
    import asyncio

    d = tmp_path / "a"
    d.mkdir()
    cert, key, ca = _make_certs(d)

    async def go():
        cfg = dict(tls_cert_file=cert, tls_key_file=key, tls_ca_file=ca,
                   interval_secs=0.1)
        a = GossipNode(GossipConfig(node_id="a", **cfg))
        await a.start()
        b = GossipNode(GossipConfig(node_id="b", seeds=[a.addr], **cfg))
        await b.start()
        try:
            for _ in range(100):
                await asyncio.sleep(0.1)
                if (any(m.node_id == "b" for m in a.alive_members())
                        and any(m.node_id == "a" for m in b.alive_members())):
                    break
            else:
                raise AssertionError("mTLS gossip never converged")

            # plaintext client: TLS handshake fails
            host, port = a.addr.rsplit(":", 1)
            try:
                r, w = await asyncio.wait_for(
                    asyncio.open_connection(host, int(port)), 2.0)
                w.write(b'{"x":1}\n')
                await w.drain()
                data = await asyncio.wait_for(r.read(100), 2.0)
                assert data == b""  # server drops the non-TLS stream
                w.close()
            except (ConnectionError, asyncio.TimeoutError, OSError):
                pass  # equally acceptable rejection

            # wrong-CA client is refused by the mutual verification
            import ssl
            import subprocess

            foreign = tmp_path / "foreign"
            foreign.mkdir()
            fcert, fkey, _fca = _make_certs(foreign, ca_name="other-ca")
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            # present the FOREIGN cert while trusting the REAL mesh CA:
            # the handshake then fails only if the SERVER enforces
            # client-cert verification (the mutual half under test)
            ctx.load_cert_chain(fcert, fkey)
            ctx.load_verify_locations(ca)
            ctx.check_hostname = False
            # TLS 1.3 delivers the server's bad-certificate alert on the
            # first IO after the (client-side-complete) handshake: the
            # attempted frame exchange must end in an error or EOF, never
            # a gossip reply
            try:
                r, w = await asyncio.wait_for(
                    asyncio.open_connection(host, int(port), ssl=ctx), 3.0)
                payload = b'{"from":"evil","addr":"x","members":[],"state":[]}'
                w.write(len(payload).to_bytes(4, "big") + payload)
                await w.drain()
                data = await asyncio.wait_for(r.read(100), 3.0)
                assert data == b"", "server answered an unauthorized client"
                w.close()
            except (ssl.SSLError, ConnectionError, OSError,
                    asyncio.TimeoutError):
                pass  # rejected during/after handshake: equally correct
        finally:
            await a.stop()
            await b.stop()

    asyncio.run(go())


def test_partial_tls_config_rejected():
    with pytest.raises(ValueError, match="mTLS"):
        GossipConfig(node_id="x", tls_cert_file="/tmp/c.crt")
