"""3rd-party provider routing: OpenAI/Anthropic/Gemini backend adapters
tested against local protocol-accurate mock provider servers through the full
gateway HTTP app (reference: routers/openai/provider/*.rs + registry.rs,
tested with mock workers per SURVEY.md §4)."""

import asyncio
import json
import threading

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.gateway.providers import ProviderRegistry, ProviderSpec
from smg_tpu.gateway.server import AppContext, build_app

# ---------------- mock upstreams ----------------


def make_mock_openai(seen: list):
    async def chat(request: web.Request):
        body = await request.json()
        seen.append({"headers": {k.lower(): v for k, v in request.headers.items()}, "body": body})
        wants_tools = bool(body.get("tools"))
        if body.get("stream"):
            resp = web.StreamResponse(headers={"content-type": "text/event-stream"})
            await resp.prepare(request)
            frames = [
                {"id": "u1", "object": "chat.completion.chunk",
                 "choices": [{"index": 0, "delta": {"role": "assistant"}}]},
                {"id": "u1", "object": "chat.completion.chunk",
                 "choices": [{"index": 0, "delta": {"content": "hi "}}]},
                {"id": "u1", "object": "chat.completion.chunk",
                 "choices": [{"index": 0, "delta": {"content": "there"}},]},
                {"id": "u1", "object": "chat.completion.chunk",
                 "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}]},
            ]
            for f in frames:
                await resp.write(f"data: {json.dumps(f)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        message = {"role": "assistant", "content": "upstream says hi"}
        finish = "stop"
        if wants_tools:
            message = {
                "role": "assistant", "content": None,
                "tool_calls": [{
                    "id": "call_1", "type": "function",
                    "function": {"name": "get_weather",
                                 "arguments": "{\"city\": \"Paris\"}"},
                }],
            }
            finish = "tool_calls"
        return web.json_response({
            "id": "upstream-1", "object": "chat.completion", "created": 1,
            "model": body["model"],
            "choices": [{"index": 0, "message": message, "finish_reason": finish}],
            "usage": {"prompt_tokens": 7, "completion_tokens": 3, "total_tokens": 10},
        })

    app = web.Application()
    app.router.add_post("/chat/completions", chat)
    return app


def make_mock_anthropic(seen: list):
    async def messages(request: web.Request):
        body = await request.json()
        seen.append({"headers": {k.lower(): v for k, v in request.headers.items()}, "body": body})
        if body.get("stream"):
            resp = web.StreamResponse(headers={"content-type": "text/event-stream"})
            await resp.prepare(request)
            events = [
                {"type": "message_start", "message": {"id": "msg_1"}},
                {"type": "content_block_start", "index": 0,
                 "content_block": {"type": "text", "text": ""}},
                {"type": "content_block_delta", "index": 0,
                 "delta": {"type": "text_delta", "text": "I'll check."}},
                {"type": "content_block_stop", "index": 0},
                {"type": "content_block_start", "index": 1,
                 "content_block": {"type": "tool_use", "id": "toolu_1",
                                   "name": "get_weather", "input": {}}},
                {"type": "content_block_delta", "index": 1,
                 "delta": {"type": "input_json_delta",
                           "partial_json": "{\"city\": \"Par"}},
                {"type": "content_block_delta", "index": 1,
                 "delta": {"type": "input_json_delta", "partial_json": "is\"}"}},
                {"type": "content_block_stop", "index": 1},
                {"type": "message_delta", "delta": {"stop_reason": "tool_use"},
                 "usage": {"output_tokens": 9}},
                {"type": "message_stop"},
            ]
            for e in events:
                await resp.write(
                    f"event: {e['type']}\ndata: {json.dumps(e)}\n\n".encode()
                )
            await resp.write_eof()
            return resp
        wants_tools = bool(body.get("tools"))
        content = [{"type": "text", "text": "bonjour"}]
        stop_reason = "end_turn"
        if wants_tools:
            content.append({"type": "tool_use", "id": "toolu_9",
                            "name": "get_weather", "input": {"city": "Paris"}})
            stop_reason = "tool_use"
        return web.json_response({
            "id": "msg_7", "type": "message", "role": "assistant",
            "model": body["model"], "content": content,
            "stop_reason": stop_reason,
            "usage": {"input_tokens": 11, "output_tokens": 5},
        })

    app = web.Application()
    app.router.add_post("/messages", messages)
    return app


def make_mock_gemini(seen: list):
    async def generate(request: web.Request):
        body = await request.json()
        seen.append({
            "headers": {k.lower(): v for k, v in request.headers.items()},
            "body": body,
            "path": request.path,
        })
        wants_tools = bool(body.get("tools"))
        parts = [{"text": "guten tag"}]
        if wants_tools:
            parts.append({"functionCall": {"name": "get_weather",
                                           "args": {"city": "Paris"}}})
        return web.json_response({
            "candidates": [{"content": {"role": "model", "parts": parts},
                            "finishReason": "STOP"}],
            "usageMetadata": {"promptTokenCount": 4, "candidatesTokenCount": 2,
                              "totalTokenCount": 6},
        })

    async def stream(request: web.Request):
        body = await request.json()
        seen.append({"body": body, "path": request.path})
        resp = web.StreamResponse(headers={"content-type": "text/event-stream"})
        await resp.prepare(request)
        frames = [
            {"candidates": [{"content": {"role": "model",
                                         "parts": [{"text": "gu"}]}}]},
            {"candidates": [{"content": {"role": "model",
                                         "parts": [{"text": "ten tag"}]},
                             "finishReason": "STOP"}]},
        ]
        for f in frames:
            await resp.write(f"data: {json.dumps(f)}\n\n".encode())
        await resp.write_eof()
        return resp

    app = web.Application()
    app.router.add_post("/models/{model}:generateContent", generate)
    app.router.add_post("/models/{model}:streamGenerateContent", stream)
    return app


# ---------------- fixture: gateway with all three providers ----------------


@pytest.fixture(scope="module")
def provider_gateway():
    loop = asyncio.new_event_loop()
    seen = {"openai": [], "anthropic": [], "gemini": []}
    ctx = AppContext(policy="round_robin")

    async def _setup():
        mocks = {}
        for kind, maker in (("openai", make_mock_openai),
                            ("anthropic", make_mock_anthropic),
                            ("gemini", make_mock_gemini)):
            server = TestServer(maker(seen[kind]))
            await server.start_server()
            mocks[kind] = server
        ctx.providers.register(ProviderSpec(
            name="openai", kind="openai",
            base_url=str(mocks["openai"].make_url("")).rstrip("/"),
            api_key="sk-test-123",
            models=["gpt-4o-mini"],
            model_map={"gpt-4o-mini": "gpt-4o-mini-2024"},
        ))
        ctx.providers.register(ProviderSpec(
            name="anthropic", kind="anthropic",
            base_url=str(mocks["anthropic"].make_url("")).rstrip("/"),
            api_key="sk-ant-test",
        ))
        ctx.providers.register(ProviderSpec(
            name="gemini", kind="gemini",
            base_url=str(mocks["gemini"].make_url("")).rstrip("/"),
            api_key="AIza-test",
        ))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc, mocks

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=60)

    tc, mocks = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.client, h.seen = run, tc, seen
    yield h
    run(tc.close())
    for s in mocks.values():
        run(s.close())
    loop.call_soon_threadsafe(loop.stop)


# ---------------- openai backend ----------------


def test_openai_provider_roundtrip(provider_gateway):
    h = provider_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "gpt-4o-mini",
            "messages": [{"role": "user", "content": "hello"}],
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    assert body["choices"][0]["message"]["content"] == "upstream says hi"
    assert body["usage"]["total_tokens"] == 10
    up = h.seen["openai"][-1]
    assert up["headers"]["authorization"] == "Bearer sk-test-123"
    assert up["body"]["model"] == "gpt-4o-mini-2024"  # model_map applied
    assert body["model"] == "gpt-4o-mini"  # gateway-facing id echoed back


def test_openai_provider_streaming(provider_gateway):
    h = provider_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "gpt-4o-mini",
            "messages": [{"role": "user", "content": "hello"}],
            "stream": True,
        })
        return await r.text()

    raw = h.run(go())
    frames = [l[6:] for l in raw.splitlines() if l.startswith("data: ")]
    assert frames[-1] == "[DONE]"
    texts = []
    for f in frames[:-1]:
        d = json.loads(f)["choices"][0]["delta"]
        if d.get("content"):
            texts.append(d["content"])
    assert "".join(texts) == "hi there"


def test_openai_provider_tool_calls(provider_gateway):
    h = provider_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "gpt-4o-mini",
            "messages": [{"role": "user", "content": "weather in paris?"}],
            "tools": [{"type": "function", "function": {
                "name": "get_weather",
                "parameters": {"type": "object",
                               "properties": {"city": {"type": "string"}}},
            }}],
        })
        return await r.json()

    body = h.run(go())
    tc = body["choices"][0]["message"]["tool_calls"][0]
    assert tc["function"]["name"] == "get_weather"
    assert json.loads(tc["function"]["arguments"]) == {"city": "Paris"}
    assert body["choices"][0]["finish_reason"] == "tool_calls"


# ---------------- anthropic backend ----------------


def test_anthropic_provider_translation(provider_gateway):
    h = provider_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "anthropic/claude-x",
            "messages": [
                {"role": "system", "content": "be brief"},
                {"role": "user", "content": "bonjour?"},
                {"role": "assistant", "content": None, "tool_calls": [{
                    "id": "call_a", "type": "function",
                    "function": {"name": "get_weather",
                                 "arguments": "{\"city\": \"Paris\"}"},
                }]},
                {"role": "tool", "tool_call_id": "call_a", "content": "{\"temp\": 21}"},
            ],
            "tools": [{"type": "function", "function": {
                "name": "get_weather",
                "parameters": {"type": "object"},
            }}],
            "max_tokens": 64,
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    up = h.seen["anthropic"][-1]
    assert up["headers"]["x-api-key"] == "sk-ant-test"
    ub = up["body"]
    assert ub["model"] == "claude-x"  # prefix stripped
    assert ub["system"] == "be brief"
    assert ub["max_tokens"] == 64
    assert ub["tools"][0]["input_schema"] == {"type": "object"}
    # assistant tool_calls became tool_use; tool reply became tool_result
    roles = [m["role"] for m in ub["messages"]]
    assert roles == ["user", "assistant", "user"]
    assert ub["messages"][1]["content"][0]["type"] == "tool_use"
    assert ub["messages"][2]["content"][0]["type"] == "tool_result"
    assert ub["messages"][2]["content"][0]["tool_use_id"] == "call_a"
    # response translated back: tool_use block -> tool_calls
    msg = body["choices"][0]["message"]
    assert msg["content"] == "bonjour"
    assert msg["tool_calls"][0]["function"]["name"] == "get_weather"
    assert body["choices"][0]["finish_reason"] == "tool_calls"
    assert body["usage"] == {"prompt_tokens": 11, "completion_tokens": 5,
                             "total_tokens": 16}


def test_anthropic_provider_streaming_tool_call(provider_gateway):
    h = provider_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "anthropic/claude-x",
            "messages": [{"role": "user", "content": "weather?"}],
            "stream": True,
        })
        return await r.text()

    raw = h.run(go())
    frames = [json.loads(l[6:]) for l in raw.splitlines()
              if l.startswith("data: ") and l != "data: [DONE]"]
    text = "".join(
        f["choices"][0]["delta"].get("content") or "" for f in frames
    )
    assert text == "I'll check."
    args = "".join(
        t["function"].get("arguments", "")
        for f in frames
        for t in f["choices"][0]["delta"].get("tool_calls") or []
    )
    assert json.loads(args) == {"city": "Paris"}
    names = [
        t["function"].get("name")
        for f in frames
        for t in f["choices"][0]["delta"].get("tool_calls") or []
        if t["function"].get("name")
    ]
    assert names == ["get_weather"]
    assert frames[-1]["choices"][0]["finish_reason"] == "tool_calls"


# ---------------- gemini backend ----------------


def test_gemini_provider_translation(provider_gateway):
    h = provider_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "gemini/gemini-pro",
            "messages": [
                {"role": "system", "content": "be nice"},
                {"role": "user", "content": "hallo"},
            ],
            "tools": [{"type": "function", "function": {
                "name": "get_weather", "parameters": {"type": "object"},
            }}],
            "temperature": 0.5,
            "max_tokens": 32,
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    up = h.seen["gemini"][-1]
    assert up["headers"]["x-goog-api-key"] == "AIza-test"
    assert up["path"].endswith("/models/gemini-pro:generateContent")
    ub = up["body"]
    assert ub["systemInstruction"]["parts"] == [{"text": "be nice"}]
    assert ub["generationConfig"]["temperature"] == 0.5
    assert ub["generationConfig"]["maxOutputTokens"] == 32
    assert ub["tools"][0]["functionDeclarations"][0]["name"] == "get_weather"
    msg = body["choices"][0]["message"]
    assert msg["content"] == "guten tag"
    assert json.loads(msg["tool_calls"][0]["function"]["arguments"]) == {"city": "Paris"}
    assert body["choices"][0]["finish_reason"] == "tool_calls"


def test_gemini_provider_streaming(provider_gateway):
    h = provider_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "gemini/gemini-pro",
            "messages": [{"role": "user", "content": "hallo"}],
            "stream": True,
        })
        return await r.text()

    raw = h.run(go())
    frames = [json.loads(l[6:]) for l in raw.splitlines()
              if l.startswith("data: ") and l != "data: [DONE]"]
    text = "".join(f["choices"][0]["delta"].get("content") or "" for f in frames)
    assert text == "guten tag"
    assert frames[-1]["choices"][0]["finish_reason"] == "stop"
    assert raw.rstrip().endswith("data: [DONE]")


# ---------------- registry + models listing ----------------


def test_provider_models_listed(provider_gateway):
    h = provider_gateway

    async def go():
        r = await h.client.get("/v1/models")
        return await r.json()

    body = h.run(go())
    ids = [m["id"] for m in body["data"]]
    assert "gpt-4o-mini" in ids


def test_unknown_model_not_provider_routed(provider_gateway):
    """Models matching no provider fall through to worker routing (and 503
    with no workers registered) — providers never swallow unknown names."""
    h = provider_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "totally-unknown",
            "messages": [{"role": "user", "content": "x"}],
        })
        return r.status

    assert h.run(go()) in (500, 503)


def test_registry_resolution_unit():
    reg = ProviderRegistry()
    reg.register(ProviderSpec(name="openai", kind="openai",
                              base_url="http://x", models=["gpt-4o"]))
    assert reg.resolve("gpt-4o") is not None
    assert reg.resolve("openai/gpt-4.1") is not None
    assert reg.resolve("claude-x") is None
    with pytest.raises(ValueError):
        reg.register(ProviderSpec(name="z", kind="nope", base_url="http://x"))