"""Parity tests: pallas paged decode attention (interpret mode) vs the XLA
gather path (``ops/attention.py::attention_decode_cached``) — the two
implementations ``runner._attn_impl_for`` switches between, including the
sliding-window and logit-softcap masks (VERDICT r4 next-round #1: Gemma-2 /
Mistral shapes must not fall back to XLA)."""

import jax.numpy as jnp
import numpy as np
import pytest

from smg_tpu.ops.attention import attention_decode_cached
from smg_tpu.ops.pallas.decode_attention import paged_attention_decode_cached


def _setup(B, H, D, K, ps, mp, N, entries, P=64, seed=0):
    rng = np.random.default_rng(seed)
    L, layer = 3, 1
    KD = K * D
    k_cache = jnp.asarray(rng.standard_normal((L, P, ps, KD)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((L, P, ps, KD)), jnp.float32)
    # distinct pages per sequence (page 0 reserved as garbage)
    pt = rng.permutation(P - 1)[: B * mp].reshape(B, mp) + 1
    page_tables = jnp.asarray(pt, jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    hk = jnp.asarray(rng.standard_normal((B, N, KD)), jnp.float32)
    hv = jnp.asarray(rng.standard_normal((B, N, KD)), jnp.float32)
    entry_positions = jnp.asarray(entries, jnp.int32)
    return q, k_cache, v_cache, hk, hv, layer, page_tables, entry_positions


CASES = [
    # B, H, D, K, entries, n_extra, softcap, window
    (2, 8, 64, 8, [100, 37], 1, None, None),      # plain, ragged entries
    (2, 8, 64, 2, [100, 37], 3, None, None),      # GQA 4:1, mid-horizon
    (2, 8, 64, 8, [100, 37], 1, 30.0, None),      # softcap only (Gemma-2)
    (2, 8, 64, 8, [100, 37], 1, None, 40),        # window cuts into the cache
    (2, 8, 64, 8, [100, 37], 2, 30.0, 40),        # softcap + window together
    (2, 8, 64, 8, [100, 37], 1, None, 7),         # window smaller than a page
    (2, 8, 64, 8, [100, 37], 1, None, 4096),      # window wider than context
    (2, 8, 64, 8, [100, 37], 1, None, 0),         # window<=0 means global
    (2, 4, 128, 2, [190, 5], 1, 50.0, 64),        # D=128 lanes, deep entry
]


@pytest.mark.parametrize("B,H,D,K,entries,n_extra,softcap,window", CASES)
def test_decode_parity_vs_xla(B, H, D, K, entries, n_extra, softcap, window):
    ps, mp, N = 16, 13, 4
    q, k_cache, v_cache, hk, hv, layer, page_tables, entry_positions = _setup(
        B, H, D, K, ps, mp, N, entries
    )
    scale = 1.0 / np.sqrt(D)
    w = None if window is None else jnp.int32(window)
    got = paged_attention_decode_cached(
        q, k_cache, v_cache, hk, hv, jnp.int32(n_extra), layer,
        page_tables, entry_positions, scale,
        softcap=softcap, window=w, interpret=True,
    )
    want = attention_decode_cached(
        q, k_cache, v_cache, hk, hv, jnp.int32(n_extra), layer,
        page_tables, entry_positions, scale,
        softcap=softcap, window=w,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_decode_window_skips_out_of_window_pages():
    """With a window, pages wholly below the window must not affect the
    output — poison them with NaN and check the kernel never reads them
    (the DMA loop starts at the window's first live page)."""
    B, H, D, K, ps, mp, N = 1, 8, 64, 8, 16, 13, 4
    entries = [150]
    window = 33  # query at 150: window covers positions 118..150 → pages 7+
    q, k_cache, v_cache, hk, hv, layer, page_tables, entry_positions = _setup(
        B, H, D, K, ps, mp, N, entries
    )
    # poison every page below the window start (positions < 112, pages 0-6)
    pt = np.asarray(page_tables)
    kc = np.array(k_cache)
    vc = np.array(v_cache)
    for i in range(7):
        kc[layer, pt[0, i]] = np.nan
        vc[layer, pt[0, i]] = np.nan
    scale = 1.0 / np.sqrt(D)
    got = paged_attention_decode_cached(
        q, jnp.asarray(kc), jnp.asarray(vc), hk, hv, jnp.int32(1), layer,
        page_tables, entry_positions, scale,
        window=jnp.int32(window), interpret=True,
    )
    assert np.isfinite(np.asarray(got)).all()
    want = attention_decode_cached(
        q, k_cache, v_cache, hk, hv, jnp.int32(1), layer,
        page_tables, entry_positions, scale, window=jnp.int32(window),
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_padded_row_stays_finite():
    """Rows whose entry position is past the table capacity (decode-bucket
    padding) must produce finite output under softcap+window too."""
    B, H, D, K, ps, mp, N = 2, 8, 64, 8, 16, 13, 4
    entries = [100, mp * 16]  # row 1 is padding (entry == capacity)
    q, k_cache, v_cache, hk, hv, layer, page_tables, entry_positions = _setup(
        B, H, D, K, ps, mp, N, entries
    )
    scale = 1.0 / np.sqrt(D)
    got = paged_attention_decode_cached(
        q, k_cache, v_cache, hk, hv, jnp.int32(1), layer,
        page_tables, entry_positions, scale,
        softcap=30.0, window=jnp.int32(24), interpret=True,
    )
    assert np.isfinite(np.asarray(got)).all()
