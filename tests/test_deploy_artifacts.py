"""Deploy-tier sanity (reference: deploy/helm/smg + docker/Dockerfile):
the chart's values cover every value referenced by the templates, and all
static YAML parses."""

import os
import re

import yaml

HERE = os.path.join(os.path.dirname(__file__), "..", "deploy")


def test_chart_and_values_parse():
    chart = yaml.safe_load(open(os.path.join(HERE, "helm/smg-tpu/Chart.yaml")))
    assert chart["name"] == "smg-tpu"
    values = yaml.safe_load(open(os.path.join(HERE, "helm/smg-tpu/values.yaml")))
    assert values["worker"]["tpu"]["resource"] == "google.com/tpu"
    assert values["gateway"]["port"] == 30000


def test_templates_reference_defined_values():
    """Every `.Values.foo.bar` path in the templates resolves in values.yaml
    (catches typos without needing helm in the image)."""
    values = yaml.safe_load(open(os.path.join(HERE, "helm/smg-tpu/values.yaml")))
    tdir = os.path.join(HERE, "helm/smg-tpu/templates")
    pattern = re.compile(r"\.Values\.([A-Za-z0-9_.]+)")
    missing = []
    for fname in os.listdir(tdir):
        src = open(os.path.join(tdir, fname)).read()
        for path in pattern.findall(src):
            node = values
            for part in path.split("."):
                if not isinstance(node, dict) or part not in node:
                    missing.append(f"{fname}: .Values.{path}")
                    break
                node = node[part]
    assert not missing, missing


def test_worker_args_match_cli_flags():
    """Flags the chart passes must exist in the CLI parser."""
    from smg_tpu.cli import build_parser

    parser = build_parser()
    known = set()
    for action in parser._subparsers._group_actions[0].choices.values():
        for a in action._actions:
            known.update(a.option_strings)
    tdir = os.path.join(HERE, "helm/smg-tpu/templates")
    flag_re = re.compile(r'"(--[a-z-]+)=')
    for fname in ("deployment-gateway.yaml", "statefulset-worker.yaml"):
        src = open(os.path.join(tdir, fname)).read()
        for flag in flag_re.findall(src):
            assert flag in known, f"{fname} passes unknown CLI flag {flag}"


def test_compose_parses():
    compose = yaml.safe_load(open(os.path.join(HERE, "docker/docker-compose.yaml")))
    assert set(compose["services"]) == {"gateway", "worker-0", "worker-1", "redis"}
    gw_cmd = compose["services"]["gateway"]["command"]
    assert any(c.startswith("--worker=") for c in gw_cmd)
