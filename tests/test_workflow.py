"""Workflow engine + job queue (reference: ``crates/workflow`` semantics —
retry/backoff, failure actions, events, resume; VERDICT r3 next-round #6)
and worker registration riding it end-to-end."""

import asyncio
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.workflow import (
    BackoffStrategy,
    FailureAction,
    JobQueue,
    RetryPolicy,
    StepDefinition,
    ValidationError,
    WorkflowDefinition,
    WorkflowEngine,
    WorkflowEvent,
)

FAST = RetryPolicy(max_attempts=3, backoff=BackoffStrategy("fixed", base=0.01))


def test_definition_validation():
    async def noop(d):
        pass

    with pytest.raises(ValidationError):
        WorkflowDefinition("empty").validate()
    d = WorkflowDefinition("dup", [
        StepDefinition("a", noop), StepDefinition("a", noop),
    ])
    with pytest.raises(ValidationError):
        d.validate()
    with pytest.raises(ValidationError):
        WorkflowDefinition("bad", [
            StepDefinition("a", noop, retry=RetryPolicy(max_attempts=0)),
        ]).validate()


def test_backoff_schedules():
    assert BackoffStrategy("fixed", base=2).delay(5) == 2
    assert BackoffStrategy("linear", increment=1, max_delay=3).delay(2) == 2
    assert BackoffStrategy("linear", increment=2, max_delay=3).delay(5) == 3
    exp = BackoffStrategy("exponential", base=1, max_delay=10)
    assert [exp.delay(i) for i in (1, 2, 3, 4, 5)] == [1, 2, 4, 8, 10]
    with pytest.raises(ValidationError):
        BackoffStrategy("bogus")


def _engine_with_events():
    engine = WorkflowEngine()
    events: list[WorkflowEvent] = []
    engine.bus.subscribe(events.append)
    return engine, events


def test_success_path_and_event_order():
    async def go():
        engine, events = _engine_with_events()

        async def step1(d):
            d["x"] = 1

        async def step2(d):
            d["y"] = d["x"] + 1

        engine.register(WorkflowDefinition("wf", [
            StepDefinition("one", step1), StepDefinition("two", step2),
        ]))
        iid = await engine.start("wf", {})
        inst = await engine.wait(iid)
        assert inst.status.value == "completed"
        assert inst.data == {"x": 1, "y": 2}
        assert [e.kind for e in events] == [
            "workflow_started", "step_started", "step_succeeded",
            "step_started", "step_succeeded", "workflow_completed",
        ]

    asyncio.run(go())


def test_retry_then_success():
    async def go():
        engine, events = _engine_with_events()
        attempts = {"n": 0}

        async def flaky(d):
            attempts["n"] += 1
            if attempts["n"] < 3:
                raise RuntimeError("transient")

        engine.register(WorkflowDefinition("wf", [
            StepDefinition("flaky", flaky, retry=FAST),
        ]))
        inst = await engine.wait(await engine.start("wf"))
        assert inst.status.value == "completed"
        assert inst.steps["flaky"].attempts == 3
        assert [e.kind for e in events].count("step_retrying") == 2

    asyncio.run(go())


def test_fail_workflow_and_continue_next_step():
    async def go():
        engine, _ = _engine_with_events()

        async def boom(d):
            raise RuntimeError("kaput")

        async def after(d):
            d["after"] = True

        engine.register(WorkflowDefinition("hard", [
            StepDefinition("boom", boom, retry=FAST),
            StepDefinition("after", after),
        ]))
        inst = await engine.wait(await engine.start("hard"))
        assert inst.status.value == "failed"
        assert inst.error == "kaput"
        assert inst.steps["after"].status.value == "pending"
        assert "after" not in inst.data

        engine.register(WorkflowDefinition("soft", [
            StepDefinition("boom", boom, retry=FAST,
                           on_failure=FailureAction.CONTINUE_NEXT_STEP),
            StepDefinition("after", after),
        ]))
        inst = await engine.wait(await engine.start("soft"))
        assert inst.status.value == "completed"
        assert inst.steps["boom"].status.value == "skipped"
        assert inst.data["after"] is True

    asyncio.run(go())


def test_retry_indefinitely_until_cancel():
    async def go():
        engine, events = _engine_with_events()

        async def forever(d):
            raise RuntimeError("nope")

        engine.register(WorkflowDefinition("wf", [
            StepDefinition(
                "forever", forever,
                retry=RetryPolicy(max_attempts=1,
                                  backoff=BackoffStrategy("fixed", base=0.01)),
                on_failure=FailureAction.RETRY_INDEFINITELY,
            ),
        ]))
        iid = await engine.start("wf")
        await asyncio.sleep(0.15)
        assert await engine.cancel(iid)
        inst = await engine.wait(iid)
        assert inst.status.value == "cancelled"
        assert inst.steps["forever"].attempts > 3

    asyncio.run(go())


def test_step_timeout():
    async def go():
        engine, _ = _engine_with_events()

        async def slow(d):
            await asyncio.sleep(5)

        engine.register(WorkflowDefinition("wf", [
            StepDefinition("slow", slow, timeout=0.05,
                           retry=RetryPolicy(max_attempts=1)),
        ]))
        inst = await engine.wait(await engine.start("wf"))
        assert inst.status.value == "failed"

    asyncio.run(go())


def test_resume_from_failure():
    """Failed step reruns on resume; succeeded steps do not repeat."""

    async def go():
        engine, _ = _engine_with_events()
        runs = {"good": 0}
        gate = {"open": False}

        async def good(d):
            runs["good"] += 1

        async def gated(d):
            if not gate["open"]:
                raise RuntimeError("closed")
            d["done"] = True

        engine.register(WorkflowDefinition("wf", [
            StepDefinition("good", good),
            StepDefinition("gated", gated, retry=FAST),
        ]))
        iid = await engine.start("wf")
        inst = await engine.wait(iid)
        assert inst.status.value == "failed"
        gate["open"] = True
        assert await engine.resume(iid)
        inst = await engine.wait(iid)
        assert inst.status.value == "completed"
        assert inst.data["done"] is True
        assert runs["good"] == 1  # not re-run
        # completed instances are not resumable
        assert not await engine.resume(iid)

    asyncio.run(go())


def test_job_queue():
    async def go():
        q = JobQueue(concurrency=2)
        try:
            async def ok():
                await asyncio.sleep(0.01)
                return 42

            async def bad():
                raise ValueError("no")

            j1, j2 = q.submit(ok, "ok"), q.submit(bad, "bad")
            j1 = await q.wait(j1.job_id)
            j2 = await q.wait(j2.job_id)
            assert j1.status == "succeeded" and j1.result == 42
            assert j2.status == "failed" and "no" in j2.error
            assert {j.job_id for j in q.list()} >= {j1.job_id, j2.job_id}
        finally:
            await q.close()

    asyncio.run(go())


# ---- e2e: registration rides the workflow through the gateway ----


@pytest.fixture(scope="module")
def reg_stack():
    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.gateway.server import AppContext, build_app
    from smg_tpu.models.config import tiny_test_config
    from smg_tpu.rpc.server import serve_worker_async
    from smg_tpu.tokenizer import MockTokenizer

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=300):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    engine = Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=2, max_seq_len=128, max_prefill_tokens=32,
            prefill_token_buckets=(32,), decode_batch_buckets=(2,),
        ),
        dtype="float32", model_id="tiny-reg",
    ), tokenizer=MockTokenizer())
    engine.start()
    ctx = AppContext(policy="round_robin")

    async def _setup():
        server = await serve_worker_async(engine, port=0, host="127.0.0.1")
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return server, tc

    server, tc = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.ctx, h.tc = run, ctx, tc
    h.worker_url = f"127.0.0.1:{server._bound_port}"
    yield h
    run(tc.close())
    run(server.stop(grace=None))
    loop.call_soon_threadsafe(loop.stop)
    engine.stop()


def test_worker_add_via_workflow_sync(reg_stack):
    h = reg_stack

    async def go():
        r = await h.tc.post("/workers", json={"url": h.worker_url,
                                              "worker_id": "wf-sync"})
        body = await r.json()
        return r.status, body

    status, body = h.run(go())
    assert status == 200, body
    assert body["added"]["worker_id"] == "wf-sync"
    assert body["workflow"]["status"] == "completed"
    steps = body["workflow"]["steps"]
    assert steps["model_info"]["status"] == "succeeded"
    assert steps["tokenizer"]["status"] == "succeeded"
    assert h.ctx.registry.get("wf-sync") is not None
    assert h.ctx.tokenizers.has("tiny-reg")

    async def cleanup():
        await h.tc.delete("/workers/wf-sync", params={"drain": "0"})

    h.run(cleanup())


def test_worker_add_async_job(reg_stack):
    h = reg_stack

    async def go():
        r = await h.tc.post("/workers", json={
            "url": h.worker_url, "worker_id": "wf-async", "async": True,
        })
        assert r.status == 202
        job_id = (await r.json())["job_id"]
        for _ in range(200):
            jr = await h.tc.get(f"/jobs/{job_id}")
            jb = await jr.json()
            if jb["status"] in ("succeeded", "failed"):
                return jb
            await asyncio.sleep(0.05)
        raise TimeoutError

    jb = h.run(go())
    assert jb["status"] == "succeeded", jb
    assert jb["result"]["status"] == "completed"
    assert h.ctx.registry.get("wf-async") is not None

    async def cleanup():
        await h.tc.delete("/workers/wf-async", params={"drain": "0"})

    h.run(cleanup())


def test_failed_registration_is_resumable(reg_stack):
    """Registration against a dead port fails after retries; once a worker
    is listening there, POST /workflows/{id}/resume completes it without
    repeating succeeded steps (reference: resume-on-failure)."""
    h = reg_stack

    async def fail_then_resume():
        # an unused port: connect succeeds (lazy gRPC), model_info fails
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        r = await h.tc.post("/workers", json={
            "url": f"127.0.0.1:{dead_port}", "worker_id": "wf-resume",
        })
        assert r.status == 502
        wr = await h.tc.get("/workflows")
        body = await wr.json()
        failed = [w for w in body["workflows"]
                  if w["status"] == "failed"
                  and w["steps"]["model_info"]["status"] == "failed"]
        assert failed, body
        iid = failed[-1]["instance_id"]
        # now point the instance at the live worker by rebinding its data —
        # operators would instead restart the worker on the same port; we
        # simulate by swapping the stored client's channel target
        inst = await h.ctx.workflows.store.load(iid)
        old_client = inst.data.get("client")
        if old_client is not None:
            await old_client.close()
        from smg_tpu.rpc.client import GrpcWorkerClient

        inst.data["client"] = GrpcWorkerClient(h.worker_url)
        inst.data["url"] = h.worker_url
        rr = await h.tc.post(f"/workflows/{iid}/resume")
        desc = await rr.json()
        assert rr.status == 200, desc
        assert desc["status"] == "completed"
        # connect step was not repeated (attempts stayed at 1)
        assert desc["steps"]["connect"]["attempts"] == 1
        assert h.ctx.registry.get("wf-resume") is not None
        await h.tc.delete("/workers/wf-resume", params={"drain": "0"})

    h.run(fail_then_resume())
