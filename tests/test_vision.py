"""Vision tower + multimodal engine plumbing (reference: the EPD encode leg —
encoder servicer vision tower + ``stages/encode.rs`` embedding handoff)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.models.config import tiny_vlm_config
from smg_tpu.models.vit import (
    VisionConfig,
    forward_vision,
    init_vision_params,
    tiny_vision_config,
)
from smg_tpu.protocols.sampling import SamplingParams


def test_vision_tower_shapes_and_determinism():
    cfg = tiny_vision_config(out_hidden_size=128)
    params = init_vision_params(cfg, jax.random.PRNGKey(0))
    gh, gw = 8, 12
    pixels = jax.random.normal(jax.random.PRNGKey(1), (gh * gw, cfg.patch_dim))
    out = forward_vision(params, cfg, pixels, (gh, gw))
    m2 = cfg.merge_size**2
    assert out.shape == (gh * gw // m2, 128)
    assert np.all(np.isfinite(np.asarray(out)))
    out2 = forward_vision(params, cfg, pixels, (gh, gw))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_vision_tower_position_sensitivity():
    """2D rope: permuting the patch grid must change the output (a tower
    ignoring positions would be permutation-equivariant after merge)."""
    cfg = tiny_vision_config()
    params = init_vision_params(cfg, jax.random.PRNGKey(0))
    gh = gw = 8
    pixels = jax.random.normal(jax.random.PRNGKey(1), (gh * gw, cfg.patch_dim))
    base = np.asarray(forward_vision(params, cfg, pixels, (gh, gw)))
    flipped = np.asarray(forward_vision(params, cfg, pixels[::-1], (gh, gw)))
    assert not np.allclose(base, flipped[::-1], atol=1e-4)


def _vlm_engine() -> Engine:
    cfg = EngineConfig(
        model=tiny_vlm_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False, dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=256, max_prefill_tokens=32,
            prefill_token_buckets=(16, 32), decode_batch_buckets=(2, 4),
        ),
        dtype="float32",
        model_id="tiny-vlm",
    )
    return Engine(cfg)


@pytest.fixture(scope="module")
def vlm():
    eng = _vlm_engine()
    yield eng
    eng.stop()


def _generate(eng, prompt, mm=None, n=8):
    done = {}
    acc = []

    def cb(out):
        acc.extend(out.new_token_ids)
        if out.finished:
            done["ids"] = list(acc)

    eng.submit(prompt, SamplingParams(temperature=0.0, max_new_tokens=n,
                                      ignore_eos=True),
               rid=f"r{np.random.randint(1 << 30)}", on_output=cb, mm_embeds=mm)
    for _ in range(200):
        eng.step()
        if "ids" in done:
            return done["ids"]
    raise TimeoutError


def test_encode_image_and_generate(vlm):
    """Full mm path: encode patches -> splice -> deterministic generation."""
    vcfg = vlm.config.model.vision
    gh, gw = 4, 8
    rng = np.random.default_rng(0)
    pixels = rng.standard_normal((gh * gw, vcfg.patch_dim)).astype(np.float32)
    embeds = vlm.encode_image(pixels, (gh, gw))
    n_tok = gh * gw // vcfg.merge_size**2
    assert embeds.shape == (n_tok, vlm.config.model.hidden_size)

    pad = vlm.config.model.image_token_id
    prompt = [5, 6, 7] + [pad] * n_tok + [9, 10]
    positions = np.arange(3, 3 + n_tok)
    ids1 = _generate(vlm, prompt, mm=(embeds, positions))
    ids2 = _generate(vlm, prompt, mm=(embeds, positions))
    assert ids1 == ids2 and len(ids1) == 8

    # different image content must change the generation's path or at least
    # the spliced-state -> check logit path differs via different output
    other = vlm.encode_image(
        rng.standard_normal((gh * gw, vcfg.patch_dim)).astype(np.float32) * 3,
        (gh, gw),
    )
    ids3 = _generate(vlm, prompt, mm=(other, positions))
    # greedy decode CAN coincide on tiny random models, but states must differ;
    # assert on the strongest observable: not all three identical tokens AND
    # identical to each other by construction of a 3x-scaled image is unlikely —
    # fall back to state check if equal
    if ids3 == ids1:
        e1 = np.asarray(embeds)
        e3 = np.asarray(other)
        assert not np.allclose(e1, e3)


def test_mm_splice_parity_with_text(vlm):
    """Splicing the model's OWN token embeddings at placeholder positions must
    reproduce the text-only generation exactly — the strongest end-to-end
    correctness check for the embedding override path."""
    table = np.asarray(vlm.runner.params["embed"], np.float32)
    pad = vlm.config.model.image_token_id
    real = [11, 12, 13, 14]
    text_prompt = [5, 6] + real + [9]
    mm_prompt = [5, 6] + [pad] * 4 + [9]
    embeds = table[real]
    positions = np.arange(2, 6)
    want = _generate(vlm, text_prompt)
    got = _generate(vlm, mm_prompt, mm=(embeds, positions))
    assert got == want


def test_mm_splice_parity_chunked(vlm):
    """Prompt longer than max_prefill_tokens: the splice must land in the
    right chunk at the right offset."""
    table = np.asarray(vlm.runner.params["embed"], np.float32)
    pad = vlm.config.model.image_token_id
    real = [21, 22, 23, 24, 25, 26]
    base = list(range(40, 40 + 60))  # 60 tokens -> chunks of 32 + rest
    text_prompt = base[:45] + real + base[45:51]
    mm_prompt = base[:45] + [pad] * 6 + base[45:51]
    positions = np.arange(45, 51)
    want = _generate(vlm, text_prompt)
    got = _generate(vlm, mm_prompt, mm=(table[real], positions))
    assert got == want


def test_mm_radix_content_keys_no_aliasing(vlm):
    """Two mm requests with identical token ids but different embeds must not
    share cached prefix state (content-hash extra keys, not cache bypass)."""
    table = np.asarray(vlm.runner.params["embed"], np.float32)
    pad = vlm.config.model.image_token_id
    prompt = [5, 6] + [pad] * 4 + list(range(30, 38))
    positions = np.arange(2, 6)
    a = _generate(vlm, prompt, mm=(table[[11, 12, 13, 14]], positions))
    b = _generate(vlm, prompt, mm=(table[[15, 16, 17, 18]], positions))
    # parity targets: the same prompts written out as text
    a_want = _generate(vlm, [5, 6, 11, 12, 13, 14] + list(range(30, 38)))
    b_want = _generate(vlm, [5, 6, 15, 16, 17, 18] + list(range(30, 38)))
    assert a == a_want and b == b_want


def test_mm_radix_cache_shares_same_image(vlm):
    """Repeating the SAME image prompt hits the radix cache (r3 weak #6:
    mm requests used to bypass caching entirely) and still generates the
    same tokens as the first pass."""
    table = np.asarray(vlm.runner.params["embed"], np.float32)
    pad = vlm.config.model.image_token_id
    # long enough that full pages (ps=16) land in the tree
    prompt = list(range(40, 56)) + [pad] * 8 + list(range(60, 70))
    positions = np.arange(16, 24)
    mm = (table[[11, 12, 13, 14, 15, 16, 17, 18]], positions)
    first = _generate(vlm, prompt, mm=mm)

    cached_seen = {}
    done = {}

    def cb(out):
        cached_seen["n"] = out.cached_tokens
        if out.finished:
            done["ids"] = True

    from smg_tpu.protocols.sampling import SamplingParams as SP

    acc = []

    def cb2(out):
        cached_seen["n"] = max(cached_seen.get("n", 0), out.cached_tokens)
        acc.extend(out.new_token_ids)
        if out.finished:
            done["ids"] = list(acc)

    vlm.submit(prompt, SP(temperature=0.0, max_new_tokens=8, ignore_eos=True),
               rid="mm-cache-hit", on_output=cb2, mm_embeds=mm)
    for _ in range(200):
        vlm.step()
        if "ids" in done:
            break
    assert done["ids"] == first
    # the shared prefix (first full pages incl. mm-salted ones) was reused
    assert cached_seen["n"] >= 16


def test_hf_config_parses_vision():
    from smg_tpu.models.config import ModelConfig

    cfg = ModelConfig.from_hf_config({
        "architectures": ["Qwen2VLForConditionalGeneration"],
        "vocab_size": 152064, "hidden_size": 2048, "intermediate_size": 11008,
        "num_hidden_layers": 28, "num_attention_heads": 16,
        "num_key_value_heads": 2, "image_token_id": 151655,
        "vision_config": {"embed_dim": 1280, "depth": 32, "num_heads": 16,
                          "patch_size": 14, "spatial_merge_size": 2,
                          "in_channels": 3},
    })
    assert cfg.vision is not None
    assert cfg.vision.out_hidden_size == 2048
    assert cfg.image_token_id == 151655
