"""Multimodal breadth (VERDICT r3 missing #7): vision processor families,
audio log-mel front-end (torch.stft as the independent oracle), video frame
sampling."""

import io

import numpy as np
import pytest

from smg_tpu.multimodal.processor import (
    Gemma3ImageProcessor,
    InternVLImageProcessor,
    LlavaImageProcessor,
    Phi3VisionImageProcessor,
    PixtralImageProcessor,
    Qwen2VLImageProcessor,
    get_image_processor,
)


def _img(h, w, seed=0):
    return np.random.default_rng(seed).integers(0, 255, (h, w, 3), np.uint8)


def test_processor_registry_families():
    assert isinstance(get_image_processor("OpenGVLab/InternVL2-8B"),
                      InternVLImageProcessor)
    assert isinstance(get_image_processor("mistralai/Pixtral-12B"),
                      PixtralImageProcessor)
    assert isinstance(get_image_processor("google/gemma-3-12b-it"),
                      Gemma3ImageProcessor)
    assert isinstance(get_image_processor("microsoft/Phi-3.5-vision"),
                      Phi3VisionImageProcessor)
    assert isinstance(get_image_processor("Qwen/Qwen2-VL-7B"),
                      Qwen2VLImageProcessor)
    assert isinstance(get_image_processor("llava-hf/llava-1.5"),
                      LlavaImageProcessor)


def test_internvl_tiling_counts():
    p = InternVLImageProcessor(tile_size=448, patch_size=14, merge_size=2,
                               max_tiles=6)
    out = p.process(_img(300, 600))  # 2:1 -> 1 row x 2 cols (+thumbnail)
    g = 448 // 14  # 32
    per_tile = (g // 2) ** 2  # 256
    assert out.num_placeholder_tokens == 3 * per_tile  # 2 tiles + thumbnail
    assert out.pixel_values.shape == (3 * g * g, 14 * 14 * 3)
    # square image, small tiles budget: 1 tile, no thumbnail
    out2 = InternVLImageProcessor(max_tiles=1).process(_img(100, 100))
    assert out2.num_placeholder_tokens == 256


def test_pixtral_aspect_preserved():
    p = PixtralImageProcessor(max_size=256, patch_size=16)
    out = p.process(_img(512, 256))  # 2:1 portrait -> 256 x 128
    assert out.grid == (16, 8)
    assert out.num_placeholder_tokens == 128
    # no merge: one token per patch
    assert out.pixel_values.shape[0] == 128


def test_gemma3_fixed_budget():
    out = Gemma3ImageProcessor().process(_img(123, 777))
    assert out.num_placeholder_tokens == 256  # (896/14/4)^2


def test_phi3_hd_views():
    p = Phi3VisionImageProcessor(base=336, patch_size=14, max_crops=4)
    out = p.process(_img(336, 672))  # 2:1 -> cols=3, rows=1 -> 3 crops + global
    g = 336 // 14  # 24
    n_views = out.grid[0] // g
    # grid consistent with the stacked patch rows (the vit tower contract)
    assert out.pixel_values.shape[0] == out.grid[0] * out.grid[1]
    assert out.grid[1] == g
    assert out.num_placeholder_tokens == n_views * (g * g) // 4
    assert out.pixel_values.shape[1] == 14 * 14 * 3
    # square image: 2x2 crops + global = 5 uniform views
    out2 = p.process(_img(200, 200))
    assert out2.grid == (5 * g, g)
    assert out2.pixel_values.shape[0] == 5 * g * g


# ---- audio ----


def _tone(freq=440.0, secs=1.0, rate=16000):
    t = np.arange(int(secs * rate)) / rate
    return (0.5 * np.sin(2 * np.pi * freq * t)).astype(np.float32)


def _wav_bytes(x, rate=16000, width=2):
    import wave

    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(width)
        w.setframerate(rate)
        if width == 2:
            w.writeframes((x * 32767).astype("<i2").tobytes())
        else:
            w.writeframes(((x * 127) + 128).astype(np.uint8).tobytes())
    return buf.getvalue()


def test_wav_decode_roundtrip():
    from smg_tpu.multimodal.audio import decode_wav

    x = _tone()
    y, rate = decode_wav(_wav_bytes(x))
    assert rate == 16000
    np.testing.assert_allclose(y, x, atol=1e-3)


def test_resample_preserves_tone():
    from smg_tpu.multimodal.audio import resample

    x = _tone(rate=8000)
    y = resample(x, 8000, 16000)
    assert abs(len(y) - 2 * len(x)) <= 1
    # dominant frequency preserved
    spec = np.abs(np.fft.rfft(y))
    peak_hz = np.argmax(spec) * 16000 / len(y)
    assert abs(peak_hz - 440.0) < 5


def test_log_mel_against_torch_stft():
    """The power spectrogram under our framing matches torch.stft (the
    independent DSP oracle); the mel projection then peaks at the tone."""
    import torch

    from smg_tpu.multimodal.audio import log_mel_spectrogram, mel_filterbank

    x = _tone(freq=1000.0)
    n_fft, hop = 400, 160
    window = np.hanning(n_fft + 1)[:-1].astype(np.float32)
    ours_frames = None
    # reproduce the framing: reflect pad + strided frames
    pad = n_fft // 2
    xp = np.pad(x, pad, mode="reflect")
    n_frames = 1 + (len(xp) - n_fft) // hop
    frames = np.lib.stride_tricks.as_strided(
        xp, shape=(n_frames, n_fft), strides=(xp.strides[0] * hop, xp.strides[0])
    )
    ours_power = np.abs(np.fft.rfft(frames * window, axis=1).T) ** 2

    t_spec = torch.stft(
        torch.from_numpy(x), n_fft, hop_length=hop,
        window=torch.from_numpy(window), center=True, pad_mode="reflect",
        return_complex=True,
    )
    t_power = (t_spec.abs() ** 2).numpy()[:, :ours_power.shape[1]]
    np.testing.assert_allclose(ours_power, t_power, rtol=1e-3, atol=1e-4)

    feats = log_mel_spectrogram(x)
    assert feats.shape[0] == 80
    # the mel bin containing 1 kHz carries the peak energy
    fb = mel_filterbank(80, n_fft, 16000)
    onek_bin = np.argmax(fb[:, int(1000 * n_fft / 16000)])
    mean_per_mel = feats.mean(axis=1)
    assert abs(int(np.argmax(mean_per_mel)) - int(onek_bin)) <= 1


def test_whisper_processor_shapes():
    from smg_tpu.multimodal.audio import WhisperAudioProcessor

    feats, tokens = WhisperAudioProcessor().process(_tone(secs=2.0))
    assert feats.shape == (80, 3000)  # 30 s padded, 10 ms hop
    assert tokens == 1500


def test_qwen2_audio_variable_length():
    from smg_tpu.multimodal.audio import Qwen2AudioProcessor

    feats, tokens = Qwen2AudioProcessor().process(_tone(secs=2.0))
    assert feats.shape[0] == 128
    assert 190 <= feats.shape[1] <= 210  # ~2 s of 10 ms hops
    assert tokens == feats.shape[1] // 2


def test_audio_bytes_path():
    from smg_tpu.multimodal.audio import WhisperAudioProcessor

    feats, tokens = WhisperAudioProcessor().process_bytes(_wav_bytes(_tone()))
    assert feats.shape == (80, 3000) and tokens == 1500


# ---- video ----


def test_video_sampling_and_tokens():
    from smg_tpu.multimodal.video import VideoProcessor, sample_frames

    frames = [_img(64, 64, seed=i) for i in range(20)]
    assert len(sample_frames(frames, 8)) == 8
    assert sample_frames(frames, 8)[0] is frames[0]
    assert sample_frames(frames, 8)[-1] is frames[-1]

    vp = VideoProcessor(Qwen2VLImageProcessor(patch_size=4, merge_size=2),
                        num_frames=4)
    out = vp.process(frames)
    assert out.num_frames == 4
    assert len(out.frame_grids) == 4
    per_frame = out.num_placeholder_tokens // 4
    assert per_frame >= 1


def test_video_gif_decode():
    from PIL import Image

    from smg_tpu.multimodal.video import decode_video_bytes

    frames = [Image.fromarray(_img(16, 16, seed=i)) for i in range(5)]
    buf = io.BytesIO()
    frames[0].save(buf, format="GIF", save_all=True,
                   append_images=frames[1:], duration=50)
    decoded = decode_video_bytes(buf.getvalue())
    assert len(decoded) == 5
    assert decoded[0].shape == (16, 16, 3)
