"""Multimodal breadth (VERDICT r3 missing #7): vision processor families,
audio log-mel front-end (torch.stft as the independent oracle), video frame
sampling."""

import io

import numpy as np
import pytest

from smg_tpu.multimodal.processor import (
    Gemma3ImageProcessor,
    InternVLImageProcessor,
    LlavaImageProcessor,
    Phi3VisionImageProcessor,
    PixtralImageProcessor,
    Qwen2VLImageProcessor,
    get_image_processor,
)


def _img(h, w, seed=0):
    return np.random.default_rng(seed).integers(0, 255, (h, w, 3), np.uint8)


def test_processor_registry_families():
    assert isinstance(get_image_processor("OpenGVLab/InternVL2-8B"),
                      InternVLImageProcessor)
    assert isinstance(get_image_processor("mistralai/Pixtral-12B"),
                      PixtralImageProcessor)
    assert isinstance(get_image_processor("google/gemma-3-12b-it"),
                      Gemma3ImageProcessor)
    assert isinstance(get_image_processor("microsoft/Phi-3.5-vision"),
                      Phi3VisionImageProcessor)
    assert isinstance(get_image_processor("Qwen/Qwen2-VL-7B"),
                      Qwen2VLImageProcessor)
    assert isinstance(get_image_processor("llava-hf/llava-1.5"),
                      LlavaImageProcessor)


def test_internvl_tiling_counts():
    p = InternVLImageProcessor(tile_size=448, patch_size=14, merge_size=2,
                               max_tiles=6)
    out = p.process(_img(300, 600))  # 2:1 -> 1 row x 2 cols (+thumbnail)
    g = 448 // 14  # 32
    per_tile = (g // 2) ** 2  # 256
    assert out.num_placeholder_tokens == 3 * per_tile  # 2 tiles + thumbnail
    assert out.pixel_values.shape == (3 * g * g, 14 * 14 * 3)
    # square image, small tiles budget: 1 tile, no thumbnail
    out2 = InternVLImageProcessor(max_tiles=1).process(_img(100, 100))
    assert out2.num_placeholder_tokens == 256


def test_pixtral_aspect_preserved():
    p = PixtralImageProcessor(max_size=256, patch_size=16)
    out = p.process(_img(512, 256))  # 2:1 portrait -> 256 x 128
    assert out.grid == (16, 8)
    assert out.num_placeholder_tokens == 128
    # no merge: one token per patch
    assert out.pixel_values.shape[0] == 128


def test_gemma3_fixed_budget():
    out = Gemma3ImageProcessor().process(_img(123, 777))
    assert out.num_placeholder_tokens == 256  # (896/14/4)^2


def test_phi3_hd_views():
    p = Phi3VisionImageProcessor(base=336, patch_size=14, max_crops=4)
    out = p.process(_img(336, 672))  # 2:1 -> cols=3, rows=1 -> 3 crops + global
    g = 336 // 14  # 24
    n_views = out.grid[0] // g
    # grid consistent with the stacked patch rows (the vit tower contract)
    assert out.pixel_values.shape[0] == out.grid[0] * out.grid[1]
    assert out.grid[1] == g
    assert out.num_placeholder_tokens == n_views * (g * g) // 4
    assert out.pixel_values.shape[1] == 14 * 14 * 3
    # square image: 2x2 crops + global = 5 uniform views
    out2 = p.process(_img(200, 200))
    assert out2.grid == (5 * g, g)
    assert out2.pixel_values.shape[0] == 5 * g * g


# ---- audio ----


def _tone(freq=440.0, secs=1.0, rate=16000):
    t = np.arange(int(secs * rate)) / rate
    return (0.5 * np.sin(2 * np.pi * freq * t)).astype(np.float32)


def _wav_bytes(x, rate=16000, width=2):
    import wave

    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(width)
        w.setframerate(rate)
        if width == 2:
            w.writeframes((x * 32767).astype("<i2").tobytes())
        else:
            w.writeframes(((x * 127) + 128).astype(np.uint8).tobytes())
    return buf.getvalue()


def test_wav_decode_roundtrip():
    from smg_tpu.multimodal.audio import decode_wav

    x = _tone()
    y, rate = decode_wav(_wav_bytes(x))
    assert rate == 16000
    np.testing.assert_allclose(y, x, atol=1e-3)


def test_resample_preserves_tone():
    from smg_tpu.multimodal.audio import resample

    x = _tone(rate=8000)
    y = resample(x, 8000, 16000)
    assert abs(len(y) - 2 * len(x)) <= 1
    # dominant frequency preserved
    spec = np.abs(np.fft.rfft(y))
    peak_hz = np.argmax(spec) * 16000 / len(y)
    assert abs(peak_hz - 440.0) < 5


def test_log_mel_against_torch_stft():
    """The power spectrogram under our framing matches torch.stft (the
    independent DSP oracle); the mel projection then peaks at the tone."""
    import torch

    from smg_tpu.multimodal.audio import log_mel_spectrogram, mel_filterbank

    x = _tone(freq=1000.0)
    n_fft, hop = 400, 160
    window = np.hanning(n_fft + 1)[:-1].astype(np.float32)
    ours_frames = None
    # reproduce the framing: reflect pad + strided frames
    pad = n_fft // 2
    xp = np.pad(x, pad, mode="reflect")
    n_frames = 1 + (len(xp) - n_fft) // hop
    frames = np.lib.stride_tricks.as_strided(
        xp, shape=(n_frames, n_fft), strides=(xp.strides[0] * hop, xp.strides[0])
    )
    ours_power = np.abs(np.fft.rfft(frames * window, axis=1).T) ** 2

    t_spec = torch.stft(
        torch.from_numpy(x), n_fft, hop_length=hop,
        window=torch.from_numpy(window), center=True, pad_mode="reflect",
        return_complex=True,
    )
    t_power = (t_spec.abs() ** 2).numpy()[:, :ours_power.shape[1]]
    np.testing.assert_allclose(ours_power, t_power, rtol=1e-3, atol=1e-4)

    feats = log_mel_spectrogram(x)
    assert feats.shape[0] == 80
    # the mel bin containing 1 kHz carries the peak energy
    fb = mel_filterbank(80, n_fft, 16000)
    onek_bin = np.argmax(fb[:, int(1000 * n_fft / 16000)])
    mean_per_mel = feats.mean(axis=1)
    assert abs(int(np.argmax(mean_per_mel)) - int(onek_bin)) <= 1


def test_whisper_processor_shapes():
    from smg_tpu.multimodal.audio import WhisperAudioProcessor

    feats, tokens = WhisperAudioProcessor().process(_tone(secs=2.0))
    assert feats.shape == (80, 3000)  # 30 s padded, 10 ms hop
    assert tokens == 1500


def test_qwen2_audio_variable_length():
    from smg_tpu.multimodal.audio import Qwen2AudioProcessor

    feats, tokens = Qwen2AudioProcessor().process(_tone(secs=2.0))
    assert feats.shape[0] == 128
    assert 190 <= feats.shape[1] <= 210  # ~2 s of 10 ms hops
    assert tokens == feats.shape[1] // 2


def test_audio_bytes_path():
    from smg_tpu.multimodal.audio import WhisperAudioProcessor

    feats, tokens = WhisperAudioProcessor().process_bytes(_wav_bytes(_tone()))
    assert feats.shape == (80, 3000) and tokens == 1500


# ---- video ----


def test_video_sampling_and_tokens():
    from smg_tpu.multimodal.video import VideoProcessor, sample_frames

    frames = [_img(64, 64, seed=i) for i in range(20)]
    assert len(sample_frames(frames, 8)) == 8
    assert sample_frames(frames, 8)[0] is frames[0]
    assert sample_frames(frames, 8)[-1] is frames[-1]

    vp = VideoProcessor(Qwen2VLImageProcessor(patch_size=4, merge_size=2),
                        num_frames=4)
    out = vp.process(frames)
    assert out.num_frames == 4
    assert len(out.frame_grids) == 4
    per_frame = out.num_placeholder_tokens // 4
    assert per_frame >= 1


def test_video_gif_decode():
    from PIL import Image

    from smg_tpu.multimodal.video import decode_video_bytes

    frames = [Image.fromarray(_img(16, 16, seed=i)) for i in range(5)]
    buf = io.BytesIO()
    frames[0].save(buf, format="GIF", save_all=True,
                   append_images=frames[1:], duration=50)
    decoded = decode_video_bytes(buf.getvalue())
    assert len(decoded) == 5
    assert decoded[0].shape == (16, 16, 3)


# ---- r5 families: llama4 / phi4 / kimi-k2.5 / qwen3-omni (VERDICT #8) ----


def test_r5_processor_registry():
    from smg_tpu.multimodal.processor import (
        KimiK25ImageProcessor,
        Llama4VisionProcessor,
        Phi4VisionProcessor,
        Qwen3OmniVisionProcessor,
    )

    assert isinstance(get_image_processor("meta-llama/Llama-4-Scout"),
                      Llama4VisionProcessor)
    assert isinstance(get_image_processor("microsoft/Phi-4-multimodal"),
                      Phi4VisionProcessor)
    assert isinstance(get_image_processor("moonshotai/Kimi-K2.5"),
                      KimiK25ImageProcessor)
    assert isinstance(get_image_processor("Qwen/Qwen3-Omni-30B"),
                      Qwen3OmniVisionProcessor)
    # phi-3 still routes to the phi3 HD transform, not phi4
    from smg_tpu.multimodal.processor import Phi3VisionImageProcessor

    assert isinstance(get_image_processor("microsoft/Phi-3.5-vision"),
                      Phi3VisionImageProcessor)


def test_llama4_tiling_tokens():
    from smg_tpu.multimodal.processor import Llama4VisionProcessor

    p = Llama4VisionProcessor()
    out = p.process(_img(336, 336))
    # single tile: no global view, 24x24 patches
    assert out.num_placeholder_tokens == 576
    out2 = p.process(_img(336, 672))  # 1x2 tiles + global
    assert out2.num_placeholder_tokens == 3 * 576
    g = 336 // 14
    assert out2.pixel_values.shape[0] == 3 * g * g


def test_phi4_token_formula():
    from smg_tpu.multimodal.processor import Phi4VisionProcessor

    p = Phi4VisionProcessor(dynamic_hd=4)
    out = p.process(_img(448, 896))  # 2:1 aspect -> 1x3 crops (sqrt rule)
    rows, cols = 1, 3
    expect = 256 + 1 + 256 * rows * cols + 16 * rows + 16
    assert out.num_placeholder_tokens == expect
    sq = p.process(_img(448, 448))  # square -> 2x2 crops
    assert sq.num_placeholder_tokens == 256 + 1 + 256 * 4 + 16 * 2 + 16


def test_kimi_zero_pads_not_resizes():
    from smg_tpu.multimodal.processor import KimiK25ImageProcessor

    p = KimiK25ImageProcessor()
    out = p.process(_img(30, 45))  # not factor-aligned; must ZERO-PAD to 56
    gh, gw = out.grid
    assert gh * 14 % (14 * 2) == 0 and gw * 14 % (14 * 2) == 0
    assert out.llm_grid == (gh // 2, gw // 2)
    assert out.num_placeholder_tokens == (gh // 2) * (gw // 2)
    # padding regions are zeros: the last row of patches for a 30-high image
    # padded to 56 contains all-zero pixels
    pv = np.asarray(out.pixel_values)
    assert np.isclose(pv, 0).any()
    # no upscale: a huge image is scaled DOWN under the side/area caps
    big = p.process(_img(14 * 600, 14 * 20))
    assert big.grid[0] <= p.side_patch_limit


def test_qwen3_omni_patch16():
    from smg_tpu.multimodal.processor import Qwen3OmniVisionProcessor

    p = Qwen3OmniVisionProcessor()
    out = p.process(_img(128, 128))
    assert out.llm_grid is not None  # planar grid (M-RoPE capable)
    # patch 16: 128 -> grid multiples of merge over 16px patches
    assert out.pixel_values.shape[1] == 16 * 16 * 3


# ---- pixel cache (VERDICT r4 missing #8: pixel_cache.rs analog) ----


def test_pixel_cache_lru_and_keys():
    from smg_tpu.multimodal.pixel_cache import (
        PixelCache,
        image_source_hash,
        processor_fingerprint,
    )
    from smg_tpu.multimodal.processor import Qwen2VLImageProcessor

    part_a = {"type": "image_url", "image_url": {"url": "data:image/png;base64,AAAA"}}
    part_b = {"type": "image_url", "image_url": {"url": "data:image/png;base64,BBBB"}}
    assert image_source_hash(part_a) == image_source_hash(dict(part_a))
    assert image_source_hash(part_a) != image_source_hash(part_b)
    fp1 = processor_fingerprint(Qwen2VLImageProcessor(patch_size=14))
    fp2 = processor_fingerprint(Qwen2VLImageProcessor(patch_size=16))
    assert fp1 != fp2  # same bytes, different geometry -> different entry

    cache = PixelCache(max_bytes=3000)
    e1 = (np.zeros((4, 256), np.float32), (2, 2), 4, None)  # ~4KB > cap: skipped
    cache.put(("k1", fp1), e1)
    assert cache.get(("k1", fp1)) is None
    small = (np.zeros((1, 128), np.float32), (1, 1), 1, None)
    cache.put(("k1", fp1), small)
    assert cache.get(("k1", fp1)) is not None
    assert cache.stats()["hits"] == 1
    # LRU eviction under the byte cap
    for i in range(10):
        cache.put((f"k{i}", fp1), small)
    assert cache.size_bytes <= 3000
    assert cache.get(("k1", fp1)) is None  # evicted as oldest


def test_pixel_cache_env_gate(monkeypatch):
    import smg_tpu.multimodal.pixel_cache as pc

    monkeypatch.setattr(pc, "_global", None)
    monkeypatch.delenv("SMG_MM_PIXEL_CACHE_MB", raising=False)
    assert pc.get_pixel_cache() is None  # disabled by default
    monkeypatch.setenv("SMG_MM_PIXEL_CACHE_MB", "8")
    monkeypatch.setattr(pc, "_global", None)
    c = pc.get_pixel_cache()
    assert c is not None and c.max_bytes == 8 * 2**20
    monkeypatch.setattr(pc, "_global", None)
