"""Reliability e2e: engine failure isolation (poison-step quarantine,
per-request deadlines, admission backpressure, graceful drain, step
watchdog) plus the original worker-death and drain-before-remove gateway
scenarios (reference: tier-2 reliability tests, model_gateway/tests/ + the
--drain-settle-secs removal semantics, main.rs:550-556).

Every failure scenario is driven through the shipped fault points in
``smg_tpu/faults.py`` — no monkeypatching of internals — so the code paths
exercised are exactly the production ones."""

import asyncio
import json
import threading
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.engine.request import QueueFullError
from smg_tpu.faults import FAULTS, InjectedFault
from smg_tpu.gateway.server import AppContext, build_app
from smg_tpu.gateway.worker_client import InProcWorkerClient, WorkerQueueFullError
from smg_tpu.gateway.workers import CircuitBreaker, Worker
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No armed fault may outlive its test."""
    yield
    FAULTS.clear()


def make_engine(watchdog_secs: float = 0.0, **sched_kw) -> Engine:
    sched = dict(
        max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
        prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(4,),
    )
    sched.update(sched_kw)
    return Engine(
        EngineConfig(
            model=tiny_test_config(),
            cache=CacheConfig(page_size=16, num_pages=128, auto_size=False, dtype="float32"),
            scheduler=SchedulerConfig(**sched),
            dtype="float32",
            model_id="tiny-test",
            step_watchdog_secs=watchdog_secs,
        )
    )


def _collector(outs: dict, rid: str):
    def cb(out):
        outs.setdefault(rid, []).append(out)
    return cb


def _drive(eng: Engine, outs: dict, rids: list, max_steps: int = 300) -> None:
    """Step the engine inline until every rid has a terminal output."""
    for _ in range(max_steps):
        eng.step()
        if all(
            rid in outs and any(o.finished for o in outs[rid]) for rid in rids
        ):
            return
    raise AssertionError(f"requests never finished: {outs}")


def _tokens(outs: dict, rid: str) -> list:
    return [t for c in outs[rid] for t in c.new_token_ids]


def assert_engine_clean(eng: Engine) -> None:
    """Zero leaked pages, radix locks, or decode lanes after all finishes.

    Asserts BOTH through the public quiescence audit (``Engine.audit()`` —
    what operators and the loadgen harness read via ``loads()``) and by
    independent internal walk, so a bug in the audit itself cannot hide a
    leak from this suite."""
    audit = eng.audit()
    assert audit["quiescent"] and audit["clean"], audit
    assert audit["leaked_pages"] == 0, audit
    assert audit["radix_locked_nodes"] == 0 == audit["radix_lock_refcounts"], audit
    assert audit["pending_callbacks"] == 0, audit
    sch = eng.scheduler
    assert sch.requests == {}, f"leaked requests: {list(sch.requests)}"
    assert all(s is None for s in sch.slots), "leaked decode lane"
    assert sch.inflight is None, "leaked in-flight frame"
    # page 0 is the reserved garbage page: free + radix-cached must cover
    # every allocatable page
    cached = sch.radix.num_cached_pages if sch.radix else 0
    assert sch.pool.free_count + cached == sch.runner.spec.num_pages - 1, (
        sch.pool.free_count, cached
    )
    # no radix node may stay pinned once every request released
    if sch.radix is not None:
        stack = [sch.radix.root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                assert child.refcount == 0, "leaked radix lock"
                stack.append(child)


class DyingClient(InProcWorkerClient):
    """Streams a couple of chunks then dies (simulated worker crash)."""

    def __init__(self, engine, die_after_chunks: int = 2):
        super().__init__(engine)
        self.die_after = die_after_chunks
        self.dead = False

    async def generate(self, req):
        n = 0
        async for chunk in super().generate(req):
            yield chunk
            n += 1
            if n >= self.die_after:
                self.dead = True
                raise ConnectionError("worker process died mid-stream")

    async def health(self) -> bool:
        return not self.dead and await super().health()


class SlowClient(InProcWorkerClient):
    """Adds per-chunk latency so requests stay in flight during a drain."""

    def __init__(self, engine, delay: float = 0.08):
        super().__init__(engine)
        self.delay = delay

    async def generate(self, req):
        async for chunk in super().generate(req):
            await asyncio.sleep(self.delay)
            yield chunk


def _gateway(workers):
    loop = asyncio.new_event_loop()
    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("tiny-test", MockTokenizer(), default=True)

    async def _setup():
        for w in workers:
            ctx.registry.add(w)
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=180):  # generous: first-compiles under CI load
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    tc = run(_setup())
    return loop, ctx, tc, run


def test_worker_dies_mid_stream_clean_error_and_heal():
    """Worker dies mid-SSE: the client sees streamed tokens, then ONE clean
    terminal error frame (no hang, no truncated garbage); the breaker opens
    and later requests route around the dead worker."""
    eng_a, eng_b = make_engine(), make_engine()
    dying = DyingClient(eng_a, die_after_chunks=1)
    w0 = Worker(worker_id="w0", client=dying, model_id="tiny-test")
    w0.circuit = CircuitBreaker(failure_threshold=1, cooldown_secs=300.0)
    w1 = Worker(worker_id="w1", client=InProcWorkerClient(eng_b), model_id="tiny-test")
    loop, ctx, tc, run = _gateway([w0, w1])
    try:
        async def stream_until_dead():
            # round_robin may pick w1 first; loop until the dying worker is hit
            for _ in range(4):
                r = await tc.post("/v1/chat/completions", json={
                    "model": "tiny-test",
                    "messages": [{"role": "user", "content": "w5 w6"}],
                    "max_tokens": 8, "temperature": 0, "ignore_eos": True,
                    "stream": True,
                })
                text = await r.text()
                if dying.dead:
                    return text
            return None

        raw = run(stream_until_dead())
        assert raw is not None, "dying worker was never selected"
        frames = [l[6:] for l in raw.splitlines() if l.startswith("data: ")]
        parsed = [json.loads(f) for f in frames if f != "[DONE]"]
        # streamed at least one real token chunk, then a terminal error frame
        assert any("choices" in p for p in parsed), frames
        assert "error" in parsed[-1], frames[-3:]
        assert w0.circuit.state.value == "open"
        assert w0.total_failures >= 1

        async def after():
            results = []
            for _ in range(4):
                r = await tc.post("/v1/chat/completions", json={
                    "model": "tiny-test",
                    "messages": [{"role": "user", "content": "w9"}],
                    "max_tokens": 3, "temperature": 0, "ignore_eos": True,
                })
                results.append(r.status)
            return results

        # registry heals: every subsequent request routes around w0
        assert run(after()) == [200, 200, 200, 200]
        assert w1.total_requests >= 4
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)
        eng_a.stop(); eng_b.stop()


def test_drain_before_remove():
    """DELETE /workers/{id}?drain=N lets in-flight streams finish: the
    draining worker takes no new requests, the live stream completes
    cleanly, and removal reports drained=true."""
    eng_a, eng_b = make_engine(), make_engine()
    slow = SlowClient(eng_a, delay=0.06)
    w0 = Worker(worker_id="w0", client=slow, model_id="tiny-test")
    w1 = Worker(worker_id="w1", client=InProcWorkerClient(eng_b), model_id="tiny-test")
    loop, ctx, tc, run = _gateway([w0, w1])
    try:
        async def go():
            # Prewarm both engines first (pin selection via draining) so the
            # drain window below measures scheduling, not first-compile time —
            # under full-suite CPU load compiles can take minutes and the
            # 600×0.05s engagement poll would time out (r3 flake).
            for warm, other in ((w0, w1), (w1, w0)):
                other.draining = True
                r = await tc.post("/v1/chat/completions", json={
                    "model": "tiny-test",
                    "messages": [{"role": "user", "content": "w1 w2"}],
                    "max_tokens": 2, "temperature": 0, "ignore_eos": True,
                })
                assert r.status == 200
                other.draining = False
            w0.total_requests = w1.total_requests = 0

            # occupy w0 with a slow stream — pin selection by draining w1
            # for the setup call (deterministic; the old round_robin hunt
            # raced with selection state left by earlier tests)
            w1.draining = True
            stream_task = asyncio.ensure_future(tc.post("/v1/chat/completions", json={
                "model": "tiny-test",
                "messages": [{"role": "user", "content": "w5 w6"}],
                "max_tokens": 10, "temperature": 0, "ignore_eos": True,
                "stream": True,
            }))
            for _ in range(600):  # first-compile under CI load can be slow
                if w0.load > 0:
                    break
                await asyncio.sleep(0.05)
            w1.draining = False
            assert w0.load > 0, "slow worker never engaged"

            # remove with drain while the stream is live
            del_task = asyncio.ensure_future(
                tc.delete("/workers/w0", params={"drain": "10"})
            )
            await asyncio.sleep(0.1)
            assert w0.draining
            # new requests during the drain land on w1 only
            r = await tc.post("/v1/chat/completions", json={
                "model": "tiny-test",
                "messages": [{"role": "user", "content": "w7"}],
                "max_tokens": 2, "temperature": 0, "ignore_eos": True,
            })
            assert r.status == 200
            assert w1.total_requests >= 1

            resp = await stream_task
            raw = await resp.text()
            del_resp = await del_task
            del_body = await del_resp.json()
            return raw, del_body

        raw, del_body = run(go(), timeout=420)
        frames = [l for l in raw.splitlines() if l.startswith("data: ")]
        assert frames[-1] == "data: [DONE]"  # the in-flight stream finished
        assert len([f for f in frames if "choices" in f]) >= 10
        assert del_body["removed"] == "w0"
        assert del_body["drained"] is True
        assert ctx.registry.get("w0") is None
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)
        eng_a.stop(); eng_b.stop()

# ---- poison-step quarantine (fault-driven, engine-level) ----


def test_poison_prefill_quarantine_survivors_byte_identical():
    """ISSUE acceptance: 3 concurrent streams + 1 deterministically-failing
    request.  The poisoned request gets exactly ONE terminal error output,
    the other 3 complete with token streams byte-identical to the same run
    without the fault, and the engine ends with zero leaked pages, radix
    locks, or decode lanes."""
    sp = SamplingParams(temperature=0.0, max_new_tokens=6, ignore_eos=True)
    prompts = {f"ok-{i}": [5 + i, 6 + i, 7 + i] for i in range(3)}

    def run(poison: bool) -> tuple[dict, Engine]:
        eng = make_engine()
        outs: dict = {}
        rids = list(prompts)
        for rid, ids in prompts.items():
            eng.submit(ids, sp, rid=rid, on_output=_collector(outs, rid))
        if poison:
            FAULTS.arm("engine.prefill", match="poison")
            eng.submit([9, 10, 11], sp, rid="poison",
                       on_output=_collector(outs, "poison"))
            rids.append("poison")
        _drive(eng, outs, rids)
        FAULTS.clear()
        return outs, eng

    poisoned, eng_p = run(poison=True)
    clean, eng_c = run(poison=False)

    # exactly one terminal error chunk for the culprit, nothing streamed
    assert len(poisoned["poison"]) == 1
    assert poisoned["poison"][0].finished
    assert poisoned["poison"][0].finish_reason == "error"
    assert poisoned["poison"][0].new_token_ids == []
    # survivors: full streams, byte-identical to the fault-free run
    for rid in prompts:
        assert _tokens(poisoned, rid) == _tokens(clean, rid)
        assert len(_tokens(poisoned, rid)) == 6
    assert_engine_clean(eng_p)
    assert_engine_clean(eng_c)
    assert eng_p.scheduler.num_quarantined == 1
    assert eng_p.healthy  # quarantine contained the failure


def test_poison_mid_prefill_chunk_quarantined():
    """A resumable (non-final) chunk that raises quarantines only its own
    request; the budget keeps metering other admissions normally."""
    sp = SamplingParams(temperature=0.0, max_new_tokens=4, ignore_eos=True)
    # prompt longer than the per-step budget -> chunked, resumable prefill
    eng = make_engine(max_prefill_tokens=16)
    outs: dict = {}
    long_prompt = [(3 * i) % 200 + 5 for i in range(40)]
    FAULTS.arm("engine.prefill", mode="after", n=1, match="longpoison")
    eng.submit(long_prompt, sp, rid="longpoison",
               on_output=_collector(outs, "longpoison"))
    eng.submit([5, 6, 7], sp, rid="short", on_output=_collector(outs, "short"))
    _drive(eng, outs, ["longpoison", "short"])
    assert outs["longpoison"][-1].finish_reason == "error"
    assert len(_tokens(outs, "short")) == 4
    assert_engine_clean(eng)


def test_decode_step_blame_newest_lane():
    """A decode-batch failure blames the most-recently-admitted lane: it is
    quarantined, surviving lanes retry within the same step and stream
    byte-identically to a fault-free run."""
    sp = SamplingParams(temperature=0.0, max_new_tokens=6, ignore_eos=True)

    def run(fault: bool) -> tuple[dict, Engine]:
        eng = make_engine()
        outs: dict = {}
        for i in range(3):
            eng.submit([5 + i, 6 + i, 7 + i], sp, rid=f"r{i}",
                       on_output=_collector(outs, f"r{i}"))
        eng.step()  # admit + prefill all three
        assert all(
            eng.scheduler.requests[f"r{i}"].status.value == "running"
            for i in range(3)
        )
        if fault:
            FAULTS.arm("engine.decode_step", mode="once")
        _drive(eng, outs, [f"r{i}" for i in range(3)])
        FAULTS.clear()
        return outs, eng

    faulted, eng_f = run(fault=True)
    clean, _eng_c = run(fault=False)
    # r2 has the highest admission serial -> blamed
    assert faulted["r2"][-1].finish_reason == "error"
    for rid in ("r0", "r1"):
        assert _tokens(faulted, rid) == _tokens(clean, rid)
        assert len(_tokens(faulted, rid)) == 6
    assert_engine_clean(eng_f)
    assert eng_f.scheduler.num_quarantined == 1
    assert eng_f.scheduler.consec_step_failures == 0  # clean steps resumed


def test_decode_poison_batch_condemned_and_unhealthy():
    """A decode fault that survives the single-lane eviction retry condemns
    the whole batch (every lane gets a terminal error), and N consecutive
    failed steps flip the engine unhealthy for loads()/health()."""
    sp = SamplingParams(temperature=0.0, max_new_tokens=6, ignore_eos=True)
    eng = make_engine()
    outs: dict = {}
    FAULTS.arm("engine.decode_step")  # always
    for i in range(2):
        eng.submit([5 + i, 6 + i, 7 + i], sp, rid=f"r{i}",
                   on_output=_collector(outs, f"r{i}"))
    _drive(eng, outs, ["r0", "r1"], max_steps=10)
    assert all(outs[r][-1].finish_reason == "error" for r in ("r0", "r1"))
    assert_engine_clean(eng)
    # rack up consecutive decode failures past the health threshold
    assert eng.healthy
    for i in range(eng.config.max_consecutive_step_failures + 1):
        eng.submit([5, 6, 7 + i], sp, rid=f"y{i}",
                   on_output=_collector(outs, f"y{i}"))
        eng.step()
    assert not eng.healthy
    assert eng.loads()["healthy"] is False
    FAULTS.clear()
    # recovery: clean steps reset the consecutive counter
    eng.submit([5, 6, 99], sp, rid="fresh", on_output=_collector(outs, "fresh"))
    _drive(eng, outs, ["fresh"])
    assert eng.healthy


# ---- per-request deadlines ----


def test_deadline_expiry_waiting_vs_running():
    """WAITING requests past deadline expire in queue; RUNNING lanes are
    aborted mid-generation — both with terminal finish_reason='timeout'."""
    eng = make_engine(max_batch_size=1)
    outs: dict = {}
    eng.submit([5, 6, 7],
               SamplingParams(temperature=0.0, max_new_tokens=64, ignore_eos=True),
               rid="run", on_output=_collector(outs, "run"), timeout_secs=0.25)
    eng.step()  # admit + prefill the running lane
    assert eng.scheduler.requests["run"].status.value == "running"
    # slot-blocked: stays WAITING until its deadline passes
    eng.submit([8, 9, 10], SamplingParams(max_new_tokens=4), rid="wait",
               on_output=_collector(outs, "wait"), timeout_secs=0.05)
    time.sleep(0.3)
    _drive(eng, outs, ["run", "wait"], max_steps=5)
    assert outs["wait"][-1].finish_reason == "timeout"
    assert outs["wait"][-1].new_token_ids == []
    assert outs["run"][-1].finish_reason == "timeout"
    assert_engine_clean(eng)
    sch = eng.scheduler
    assert sch.num_deadline_waiting == 1
    assert sch.num_deadline_running == 1
    loads = eng.loads()
    assert loads["deadline_expirations_waiting"] == 1
    assert loads["deadline_expirations_running"] == 1


def test_generate_timeout_is_a_finish_not_a_raise():
    """Satellite: Engine.generate's wait is parameterized and rides the
    deadline plumbing — sync callers get a 'timeout' finish instead of a
    raised TimeoutError with an orphaned abort."""
    eng = make_engine()
    res = eng.generate(
        prompt_ids=[5, 6, 7],
        sampling=SamplingParams(temperature=0.0, max_new_tokens=10_000,
                                ignore_eos=True),
        timeout_secs=0.2,
    )
    assert res.finish_reason == "timeout"
    assert_engine_clean(eng)


# ---- admission backpressure ----


def test_bounded_queue_rejects_at_submit():
    eng = make_engine(max_queued_requests=1)
    sp = SamplingParams(max_new_tokens=4)
    eng.submit([1, 2, 3], sp, rid="a")  # fills the (unstarted) queue
    with pytest.raises(QueueFullError):
        eng.submit([1, 2, 4], sp, rid="b")
    assert eng.scheduler.num_queue_rejections == 1
    assert eng.loads()["queue_rejections"] == 1


def test_bounded_queue_token_cap():
    eng = make_engine(max_queued_tokens=8)
    sp = SamplingParams(max_new_tokens=4)
    eng.submit([1, 2, 3, 4, 5], sp, rid="a")
    with pytest.raises(QueueFullError):
        eng.submit([1, 2, 3, 4, 5], sp, rid="b")


# ---- step watchdog ----


def test_watchdog_stall_detection_and_recovery():
    """A wedged device fetch (injected hang) flips the engine unhealthy via
    the watchdog thread; progress resuming clears the stall and the request
    still completes."""
    eng = make_engine(watchdog_secs=0.3)
    eng.start()
    try:
        # warm the compile caches first so the injected hang dominates
        eng.generate(prompt_ids=[5, 6, 7],
                     sampling=SamplingParams(temperature=0.0, max_new_tokens=4,
                                             ignore_eos=True))
        stalls_before = eng.num_watchdog_stalls
        FAULTS.arm("engine.device_fetch", mode="once", action="hang", delay=2.0)
        outs: dict = {}
        eng.submit([8, 9, 10],
                   SamplingParams(temperature=0.0, max_new_tokens=4,
                                  ignore_eos=True),
                   rid="w", on_output=_collector(outs, "w"))
        saw_unhealthy = False
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if not eng.healthy:
                saw_unhealthy = True
            if outs.get("w") and outs["w"][-1].finished:
                break
            time.sleep(0.02)
        assert saw_unhealthy, "watchdog never flagged the stall"
        assert eng.num_watchdog_stalls > stalls_before
        assert outs["w"][-1].finished
        deadline = time.monotonic() + 10
        while not eng.healthy and time.monotonic() < deadline:
            time.sleep(0.02)
        assert eng.healthy, "stall never cleared after progress resumed"
    finally:
        eng.stop()


# ---- graceful drain ----


def test_drain_on_stop():
    """engine.stop(drain=True): admission stops, queued requests get a
    terminal abort (clients see an end, never a hang), running lanes finish
    their streams completely."""
    eng = make_engine(max_batch_size=1)
    eng.start()
    outs: dict = {}
    eng.submit([5, 6, 7],
               SamplingParams(temperature=0.0, max_new_tokens=30, ignore_eos=True),
               rid="run", on_output=_collector(outs, "run"))
    eng.submit([8, 9, 10], SamplingParams(max_new_tokens=4), rid="wait",
               on_output=_collector(outs, "wait"))
    deadline = time.monotonic() + 120
    while "run" not in outs and time.monotonic() < deadline:
        time.sleep(0.01)  # the running lane engaged
    eng.stop(drain=True, timeout=120)
    assert outs["wait"][-1].finished
    assert outs["wait"][-1].finish_reason == "abort"
    assert outs["run"][-1].finished
    assert outs["run"][-1].finish_reason in ("length", "stop")
    assert len(_tokens(outs, "run")) == 30
    assert_engine_clean(eng)


# ---- queue-full through the gateway (retry-other-worker / 429) ----


def _frozen_full_worker(worker_id: str) -> tuple:
    """A worker whose engine queue is full and whose loop is stopped, so
    every generate hits admission backpressure deterministically."""
    eng = make_engine(max_queued_requests=1)
    client = InProcWorkerClient(eng)
    eng.stop()  # freeze the loop: the queued filler never drains
    eng.submit([1, 2, 3], SamplingParams(max_new_tokens=4), rid="filler")
    return eng, Worker(worker_id=worker_id, client=client, model_id="tiny-test")


def test_queue_full_routes_to_other_worker_then_429():
    """Engine backpressure surfaces as retry-another-worker: requests
    succeed on the healthy worker, the full worker's breaker stays closed
    (load is not fault) — and with no capacity anywhere the front door
    answers 429."""
    eng_a, w0 = _frozen_full_worker("w0")
    eng_b = make_engine()
    w1 = Worker(worker_id="w1", client=InProcWorkerClient(eng_b),
                model_id="tiny-test")
    loop, ctx, tc, run = _gateway([w0, w1])
    try:
        async def go():
            statuses = []
            for _ in range(4):
                r = await tc.post("/v1/chat/completions", json={
                    "model": "tiny-test",
                    "messages": [{"role": "user", "content": "w5 w6"}],
                    "max_tokens": 2, "temperature": 0, "ignore_eos": True,
                })
                statuses.append(r.status)
            return statuses

        assert run(go()) == [200, 200, 200, 200]
        # backpressure is not failure: the full worker's breaker never moved
        assert w0.circuit.state.value == "closed"
        assert w0.total_failures == 0
        assert eng_a.scheduler.num_queue_rejections >= 1

        # all capacity gone: only the full worker remains -> 429 retry-later
        ctx.registry.remove("w1")

        async def go429():
            r = await tc.post("/v1/chat/completions", json={
                "model": "tiny-test",
                "messages": [{"role": "user", "content": "w9"}],
                "max_tokens": 2, "temperature": 0, "ignore_eos": True,
            })
            return r.status, await r.json()

        status, body = run(go429())
        assert status == 429, body
        assert "capacity" in body["error"]["message"]
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)
        eng_a.stop()
        eng_b.stop()


# ---- satellite: circuit breaker half-open probe gating ----


def test_half_open_admits_single_probe():
    """HALF_OPEN admits ONE in-flight probe, not the whole backed-up queue
    (half-open flood).  allow() stays read-only (health endpoints / policy
    filters must not starve real probes); the slot is claimed at dispatch
    (begin_probe via the load guard), freed by the probe's outcome, and
    self-heals if the outcome never lands."""
    cb = CircuitBreaker(failure_threshold=1, success_threshold=1,
                        cooldown_secs=0.05)
    cb.record_failure()
    assert cb.state.value == "open"
    assert not cb.allow()
    time.sleep(0.06)
    assert cb.state.value == "half_open"
    assert cb.allow() is True       # probe slot free
    assert cb.allow() is True       # read-only: no consumption
    cb.begin_probe()                # a request dispatched: slot claimed
    assert cb.allow() is False      # flood gated
    assert cb.allow() is False
    cb.record_success()             # probe succeeded -> closed
    assert cb.state.value == "closed"
    assert cb.allow() is True

    # a probe whose outcome never lands (client vanished) must not wedge
    # the breaker: the stale slot expires after the cooldown
    cb2 = CircuitBreaker(failure_threshold=1, success_threshold=1,
                         cooldown_secs=0.05)
    cb2.record_failure()
    time.sleep(0.06)
    cb2.begin_probe()
    assert cb2.allow() is False
    time.sleep(0.06)
    assert cb2.allow() is True

    # a failed probe re-opens the circuit
    cb3 = CircuitBreaker(failure_threshold=1, success_threshold=1,
                         cooldown_secs=0.05)
    cb3.record_failure()
    time.sleep(0.06)
    cb3.begin_probe()
    cb3.record_failure()
    assert cb3.state.value == "open"
    assert not cb3.allow()


def test_half_open_gates_through_worker_guard():
    """End to end through Worker: the first half-open dispatch claims the
    probe, concurrent selection sees the worker unavailable until the probe
    reports."""
    eng = make_engine()
    w = Worker(worker_id="wp", client=InProcWorkerClient(eng), model_id="m")
    w.circuit = CircuitBreaker(failure_threshold=1, success_threshold=1,
                               cooldown_secs=0.05)
    w.circuit.record_failure()
    assert not w.is_available()
    time.sleep(0.06)
    assert w.is_available()
    guard = w.acquire()             # the probe dispatch
    assert not w.is_available()     # flood gated while the probe flies
    guard.release(success=True)
    assert w.is_available()         # closed again (threshold 1)
    assert w.circuit.state.value == "closed"
    eng.stop()


def test_total_failures_incremented_under_lock():
    """Satellite: Worker.total_failures increments under the worker lock —
    concurrent guard releases must never lose counts."""
    eng = make_engine()
    w = Worker(worker_id="wx", client=InProcWorkerClient(eng), model_id="tiny-test")
    N = 32
    barrier = threading.Barrier(N)

    def one():
        guard = w.acquire()
        barrier.wait()
        guard.release(success=False)

    threads = [threading.Thread(target=one) for _ in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert w.total_failures == N
    assert w.load == 0
    eng.stop()


# ---- satellite: HealthMonitor state cleanup on worker removal ----


def test_health_monitor_cleans_up_removed_workers():
    from prometheus_client import CollectorRegistry

    from smg_tpu.gateway.health import HealthConfig, HealthMonitor
    from smg_tpu.gateway.observability import Metrics
    from smg_tpu.gateway.worker_client import WorkerClient
    from smg_tpu.gateway.workers import WorkerRegistry

    class StubClient(WorkerClient):
        async def health(self):
            return True

    registry = WorkerRegistry()
    metrics = Metrics(registry=CollectorRegistry())
    monitor = HealthMonitor(registry, HealthConfig(), metrics)
    w = Worker(worker_id="gone", client=StubClient(), model_id="m")
    registry.add(w)
    asyncio.run(monitor.check_all())
    assert "gone" in monitor._succs
    assert ("gone",) in monitor.metrics.worker_healthy._metrics

    registry.remove("gone")
    assert "gone" not in monitor._succs
    assert "gone" not in monitor._fails
    assert ("gone",) not in monitor.metrics.worker_healthy._metrics
    assert ("gone",) not in monitor.metrics.worker_load._metrics


# ---- satellite: per-chunk stream idle timeout (rpc client) ----


def test_stream_idle_timeout_treats_silence_as_failure():
    from smg_tpu.rpc.client import StreamIdleTimeout, iter_with_idle_timeout

    class FakeCall:
        """Async iterator: one chunk, then silence forever."""

        def __init__(self):
            self.cancelled = False
            self._sent = False

        def __aiter__(self):
            return self

        async def __anext__(self):
            if not self._sent:
                self._sent = True
                return "chunk-1"
            await asyncio.sleep(3600)  # wedged worker: no further chunks

        def cancel(self):
            self.cancelled = True

    async def go():
        call = FakeCall()
        got = []
        with pytest.raises(StreamIdleTimeout):
            async for chunk in iter_with_idle_timeout(call, 0.05, "w:1"):
                got.append(chunk)
        return call, got

    call, got = asyncio.run(go())
    assert got == ["chunk-1"]  # progress before the stall was delivered
    assert call.cancelled      # the wedged call was torn down

    async def clean():
        class Done:
            def __init__(self):
                self.n = 0

            def __aiter__(self):
                return self

            async def __anext__(self):
                self.n += 1
                if self.n > 3:
                    raise StopAsyncIteration
                return self.n

            def cancel(self):
                pass

        return [c async for c in iter_with_idle_timeout(Done(), 0.5, "w:1")]

    assert asyncio.run(clean()) == [1, 2, 3]


# ---- review-fix regressions ----


def test_submit_during_drain_rejected_not_hung():
    """A submit landing after stop(drain=True) must get a retryable
    rejection, never sit in a queue no admission loop will touch."""
    eng = make_engine()
    eng.start()
    eng.stop(drain=True, timeout=10)
    with pytest.raises(QueueFullError):
        eng.submit([1, 2, 3], SamplingParams(max_new_tokens=2), rid="late")


def test_consecutive_prefill_failures_flip_unhealthy():
    """A worker failing EVERY prefill must eventually report unhealthy —
    quarantined steps complete, but they are not clean steps."""
    eng = make_engine()
    outs: dict = {}
    FAULTS.arm("engine.prefill")  # always
    for i in range(eng.config.max_consecutive_step_failures + 1):
        eng.submit([5, 6, 7 + i], SamplingParams(max_new_tokens=2),
                   rid=f"p{i}", on_output=_collector(outs, f"p{i}"))
        eng.step()
        assert outs[f"p{i}"][-1].finish_reason == "error"
    assert not eng.healthy
    FAULTS.clear()
    # one genuinely clean step (with real work) resets the streak
    eng.submit([5, 6, 99], SamplingParams(temperature=0.0, max_new_tokens=2,
                                          ignore_eos=True),
               rid="ok", on_output=_collector(outs, "ok"))
    _drive(eng, outs, ["ok"])
    assert eng.healthy


def test_exhausted_grpc_budget_is_not_unlimited():
    """timeout_secs=0.0 (budget burned by retries) must round to a tiny
    positive deadline on the wire, not the proto's 0=no-deadline sentinel."""
    from smg_tpu.rpc import scheduler_pb2 as pb

    # the client-side clamp: None -> 0 (no deadline), 0.0 -> epsilon
    assert (0.0 if None is None else max(None, 1e-3)) == 0.0
    msg = pb.GenerateRequestProto(rid="x", timeout_secs=max(0.0, 1e-3))
    assert pb.GenerateRequestProto.FromString(
        msg.SerializeToString()
    ).timeout_secs > 0.0
    # and the engine treats an epsilon deadline as expire-now, not run-forever
    eng = make_engine()
    outs: dict = {}
    eng.submit([5, 6, 7], SamplingParams(max_new_tokens=1000, ignore_eos=True),
               rid="spent", on_output=_collector(outs, "spent"),
               timeout_secs=0.001)
    time.sleep(0.01)
    _drive(eng, outs, ["spent"], max_steps=5)
    assert outs["spent"][-1].finish_reason == "timeout"


def test_first_chunk_timeout_separate_from_idle_bound():
    """Queue wait + prefill (time to FIRST chunk) must not trip the
    inter-chunk idle bound — only the longer wedge backstop applies there."""
    from smg_tpu.rpc.client import StreamIdleTimeout, iter_with_idle_timeout

    class SlowStart:
        """First chunk after a delay LONGER than the idle bound, then a
        quick second chunk, then silence."""

        def __init__(self):
            self.cancelled = False
            self.n = 0

        def __aiter__(self):
            return self

        async def __anext__(self):
            self.n += 1
            if self.n == 1:
                await asyncio.sleep(0.15)  # busy worker: > idle, < backstop
                return "first"
            if self.n == 2:
                return "second"
            await asyncio.sleep(3600)  # wedged mid-stream

        def cancel(self):
            self.cancelled = True

    async def go():
        call = SlowStart()
        got = []
        with pytest.raises(StreamIdleTimeout):
            async for c in iter_with_idle_timeout(
                call, 0.05, "w:1", first_chunk_timeout_secs=1.0
            ):
                got.append(c)
        return call, got

    call, got = asyncio.run(go())
    assert got == ["first", "second"]  # slow start survived the idle bound
    assert call.cancelled              # mid-stream silence did not


def test_reliability_locks_inversion_free_under_sentinel():
    """Lock-order sentinel over the failure-isolation hot paths: engine
    RLock + flight-recorder lock + quarantine dump path, exercised by a
    poison-decode quarantine with survivors, record ZERO order inversions.
    (scripts/ci_checks.sh additionally runs this whole suite with
    SMG_LOCK_SENTINEL=1, which fails any test at the acquisition closing an
    inversion cycle.)"""
    from smg_tpu.analysis.runtime_guards import lock_order_sentinel

    with lock_order_sentinel() as s:
        eng = make_engine()  # locks created inside the armed block
        outs: dict = {}
        rids = []
        for i in range(3):
            rid = f"sent-{i}"
            rids.append(rid)
            eng.submit(
                [(5 * i + j) % 90 + 5 for j in range(16)],
                SamplingParams(temperature=0.0, max_new_tokens=12,
                               ignore_eos=True),
                rid=rid, on_output=_collector(outs, rid),
            )
        # poison one decode step mid-flight: quarantine + flight-recorder
        # dump runs with the engine lock held (the nesting under test)
        FAULTS.arm("engine.decode_step", mode="once")
        _drive(eng, outs, rids)
        quarantined = [
            r for r in rids
            if any(o.finish_reason == "error" for o in outs[r])
        ]
        assert len(quarantined) == 1  # blame fell on exactly one lane
        eng.stop(drain=True, timeout=5.0)
        assert_engine_clean(eng)
    assert s.inversions == [], s.format_inversions()
