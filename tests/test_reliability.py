"""Reliability e2e: worker death mid-stream and drain-before-remove
(reference: tier-2 reliability tests, model_gateway/tests/ + the
--drain-settle-secs removal semantics, main.rs:550-556)."""

import asyncio
import json
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.gateway.server import AppContext, build_app
from smg_tpu.gateway.worker_client import InProcWorkerClient
from smg_tpu.gateway.workers import CircuitBreaker, Worker
from smg_tpu.models.config import tiny_test_config
from smg_tpu.tokenizer import MockTokenizer


def make_engine() -> Engine:
    return Engine(
        EngineConfig(
            model=tiny_test_config(),
            cache=CacheConfig(page_size=16, num_pages=128, auto_size=False, dtype="float32"),
            scheduler=SchedulerConfig(
                max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
                prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(4,),
            ),
            dtype="float32",
            model_id="tiny-test",
        )
    )


class DyingClient(InProcWorkerClient):
    """Streams a couple of chunks then dies (simulated worker crash)."""

    def __init__(self, engine, die_after_chunks: int = 2):
        super().__init__(engine)
        self.die_after = die_after_chunks
        self.dead = False

    async def generate(self, req):
        n = 0
        async for chunk in super().generate(req):
            yield chunk
            n += 1
            if n >= self.die_after:
                self.dead = True
                raise ConnectionError("worker process died mid-stream")

    async def health(self) -> bool:
        return not self.dead and await super().health()


class SlowClient(InProcWorkerClient):
    """Adds per-chunk latency so requests stay in flight during a drain."""

    def __init__(self, engine, delay: float = 0.08):
        super().__init__(engine)
        self.delay = delay

    async def generate(self, req):
        async for chunk in super().generate(req):
            await asyncio.sleep(self.delay)
            yield chunk


def _gateway(workers):
    loop = asyncio.new_event_loop()
    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("tiny-test", MockTokenizer(), default=True)

    async def _setup():
        for w in workers:
            ctx.registry.add(w)
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=180):  # generous: first-compiles under CI load
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    tc = run(_setup())
    return loop, ctx, tc, run


def test_worker_dies_mid_stream_clean_error_and_heal():
    """Worker dies mid-SSE: the client sees streamed tokens, then ONE clean
    terminal error frame (no hang, no truncated garbage); the breaker opens
    and later requests route around the dead worker."""
    eng_a, eng_b = make_engine(), make_engine()
    dying = DyingClient(eng_a, die_after_chunks=1)
    w0 = Worker(worker_id="w0", client=dying, model_id="tiny-test")
    w0.circuit = CircuitBreaker(failure_threshold=1, cooldown_secs=300.0)
    w1 = Worker(worker_id="w1", client=InProcWorkerClient(eng_b), model_id="tiny-test")
    loop, ctx, tc, run = _gateway([w0, w1])
    try:
        async def stream_until_dead():
            # round_robin may pick w1 first; loop until the dying worker is hit
            for _ in range(4):
                r = await tc.post("/v1/chat/completions", json={
                    "model": "tiny-test",
                    "messages": [{"role": "user", "content": "w5 w6"}],
                    "max_tokens": 8, "temperature": 0, "ignore_eos": True,
                    "stream": True,
                })
                text = await r.text()
                if dying.dead:
                    return text
            return None

        raw = run(stream_until_dead())
        assert raw is not None, "dying worker was never selected"
        frames = [l[6:] for l in raw.splitlines() if l.startswith("data: ")]
        parsed = [json.loads(f) for f in frames if f != "[DONE]"]
        # streamed at least one real token chunk, then a terminal error frame
        assert any("choices" in p for p in parsed), frames
        assert "error" in parsed[-1], frames[-3:]
        assert w0.circuit.state.value == "open"
        assert w0.total_failures >= 1

        async def after():
            results = []
            for _ in range(4):
                r = await tc.post("/v1/chat/completions", json={
                    "model": "tiny-test",
                    "messages": [{"role": "user", "content": "w9"}],
                    "max_tokens": 3, "temperature": 0, "ignore_eos": True,
                })
                results.append(r.status)
            return results

        # registry heals: every subsequent request routes around w0
        assert run(after()) == [200, 200, 200, 200]
        assert w1.total_requests >= 4
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)
        eng_a.stop(); eng_b.stop()


def test_drain_before_remove():
    """DELETE /workers/{id}?drain=N lets in-flight streams finish: the
    draining worker takes no new requests, the live stream completes
    cleanly, and removal reports drained=true."""
    eng_a, eng_b = make_engine(), make_engine()
    slow = SlowClient(eng_a, delay=0.06)
    w0 = Worker(worker_id="w0", client=slow, model_id="tiny-test")
    w1 = Worker(worker_id="w1", client=InProcWorkerClient(eng_b), model_id="tiny-test")
    loop, ctx, tc, run = _gateway([w0, w1])
    try:
        async def go():
            # Prewarm both engines first (pin selection via draining) so the
            # drain window below measures scheduling, not first-compile time —
            # under full-suite CPU load compiles can take minutes and the
            # 600×0.05s engagement poll would time out (r3 flake).
            for warm, other in ((w0, w1), (w1, w0)):
                other.draining = True
                r = await tc.post("/v1/chat/completions", json={
                    "model": "tiny-test",
                    "messages": [{"role": "user", "content": "w1 w2"}],
                    "max_tokens": 2, "temperature": 0, "ignore_eos": True,
                })
                assert r.status == 200
                other.draining = False
            w0.total_requests = w1.total_requests = 0

            # occupy w0 with a slow stream — pin selection by draining w1
            # for the setup call (deterministic; the old round_robin hunt
            # raced with selection state left by earlier tests)
            w1.draining = True
            stream_task = asyncio.ensure_future(tc.post("/v1/chat/completions", json={
                "model": "tiny-test",
                "messages": [{"role": "user", "content": "w5 w6"}],
                "max_tokens": 10, "temperature": 0, "ignore_eos": True,
                "stream": True,
            }))
            for _ in range(600):  # first-compile under CI load can be slow
                if w0.load > 0:
                    break
                await asyncio.sleep(0.05)
            w1.draining = False
            assert w0.load > 0, "slow worker never engaged"

            # remove with drain while the stream is live
            del_task = asyncio.ensure_future(
                tc.delete("/workers/w0", params={"drain": "10"})
            )
            await asyncio.sleep(0.1)
            assert w0.draining
            # new requests during the drain land on w1 only
            r = await tc.post("/v1/chat/completions", json={
                "model": "tiny-test",
                "messages": [{"role": "user", "content": "w7"}],
                "max_tokens": 2, "temperature": 0, "ignore_eos": True,
            })
            assert r.status == 200
            assert w1.total_requests >= 1

            resp = await stream_task
            raw = await resp.text()
            del_resp = await del_task
            del_body = await del_resp.json()
            return raw, del_body

        raw, del_body = run(go(), timeout=420)
        frames = [l for l in raw.splitlines() if l.startswith("data: ")]
        assert frames[-1] == "data: [DONE]"  # the in-flight stream finished
        assert len([f for f in frames if "choices" in f]) >= 10
        assert del_body["removed"] == "w0"
        assert del_body["drained"] is True
        assert ctx.registry.get("w0") is None
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)
        eng_a.stop(); eng_b.stop()