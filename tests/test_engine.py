"""End-to-end engine tests on CPU: continuous batching, prefix cache,
stop handling, page-pressure preemption."""

import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


def make_engine(num_pages=128, max_batch=8, max_seq_len=256, **sched_kw) -> Engine:
    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=num_pages, auto_size=False, dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=max_batch,
            max_seq_len=max_seq_len,
            max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64),
            decode_batch_buckets=(4, 8),
            **sched_kw,
        ),
        dtype="float32",
    )
    return Engine(cfg, tokenizer=MockTokenizer())


@pytest.fixture(scope="module")
def engine():
    return make_engine()


def greedy(max_new=8, **kw) -> SamplingParams:
    return SamplingParams(temperature=0.0, max_new_tokens=max_new, ignore_eos=True, **kw)


def test_basic_generate(engine):
    res = engine.generate(prompt_ids=list(range(5, 25)), sampling=greedy(8))
    assert len(res.token_ids) == 8
    assert res.finish_reason == "length"
    assert res.prompt_tokens == 20
    assert res.output_tokens == 8
    assert res.text  # detokenized via MockTokenizer


def test_greedy_deterministic_and_prefix_cached(engine):
    prompt = list(range(30, 70))  # 40 tokens
    r1 = engine.generate(prompt_ids=prompt, sampling=greedy(6))
    r2 = engine.generate(prompt_ids=prompt, sampling=greedy(6))
    assert r1.token_ids == r2.token_ids
    assert r1.cached_tokens == 0
    # 40 tokens -> 2 full pages cached; match capped at prompt_len-1 => 32
    assert r2.cached_tokens == 32


def test_prefix_cache_does_not_change_output(engine):
    prompt = list(range(100, 180))  # 80 tokens
    r1 = engine.generate(prompt_ids=prompt, sampling=greedy(10))
    r2 = engine.generate(prompt_ids=prompt, sampling=greedy(10))
    assert r2.cached_tokens > 0
    assert r1.token_ids == r2.token_ids


def test_stop_token_ids(engine):
    probe = engine.generate(prompt_ids=list(range(5, 15)), sampling=greedy(4))
    stop_tok = probe.token_ids[2]
    res = engine.generate(
        prompt_ids=list(range(5, 15)),
        sampling=SamplingParams(
            temperature=0.0, max_new_tokens=16, ignore_eos=True, stop_token_ids=[stop_tok]
        ),
    )
    assert res.finish_reason == "stop"
    assert res.matched_stop == stop_tok
    assert res.token_ids[-1] == stop_tok
    assert len(res.token_ids) == 3


def test_stop_string(engine):
    probe = engine.generate(prompt_ids=list(range(40, 50)), sampling=greedy(6))
    # the mock tokenizer renders token i as "w{i}"; stop on the 3rd token's text
    stop_word = f"w{probe.token_ids[2]}"
    res = engine.generate(
        prompt_ids=list(range(40, 50)),
        sampling=SamplingParams(
            temperature=0.0, max_new_tokens=16, ignore_eos=True, stop=[stop_word]
        ),
    )
    assert res.finish_reason == "stop"
    assert res.matched_stop == stop_word
    assert stop_word not in res.text
    assert len(res.token_ids) < 16


def test_concurrent_requests_interleave(engine):
    results = {}
    rids = []
    for i in range(6):
        prompt = list(range(10 + i * 7, 30 + i * 7))
        rid = engine.submit(
            prompt, greedy(5 + i % 3), on_output=lambda o, i=i: results.setdefault(i, []).append(o)
        )
        rids.append(rid)
    for _ in range(200):
        engine.step()
        if len([k for k, v in results.items() if v and v[-1].finished]) == 6:
            break
    assert all(results[i][-1].finished for i in range(6))
    for i in range(6):
        total = sum(len(o.new_token_ids) for o in results[i])
        assert total == 5 + i % 3


def test_sequential_equals_batched(engine):
    prompts = [list(range(200 + i * 11, 220 + i * 11)) for i in range(4)]
    solo = [engine.generate(prompt_ids=p, sampling=greedy(6)).token_ids for p in prompts]
    engine.flush_cache()
    results = {}
    for i, p in enumerate(prompts):
        engine.submit(p, greedy(6), on_output=lambda o, i=i: results.setdefault(i, []).append(o))
    for _ in range(200):
        engine.step()
        if len([k for k, v in results.items() if v and v[-1].finished]) == 4:
            break
    batched = [
        [t for o in results[i] for t in o.new_token_ids] for i in range(4)
    ]
    assert batched == solo


def test_kv_events_emitted(engine):
    batches = []
    unsub = engine.events.subscribe(batches.append)
    engine.generate(prompt_ids=list(range(300, 340)), sampling=greedy(4))
    unsub()
    stored = [e for b in batches for e in b.events if type(e).__name__ == "BlockStored"]
    assert stored, "expected BlockStored events after a completed request"
    assert all(len(e.block_hashes) * e.block_size == len(e.token_ids) for e in stored)


def test_abort_waiting_and_running(engine):
    rid = engine.submit(list(range(5, 25)), greedy(50))
    assert engine.abort(rid)
    assert not engine.scheduler.has_work() or engine.scheduler.requests.get(rid) is None


def test_max_new_tokens_zero(engine):
    res = engine.generate(prompt_ids=list(range(5, 15)), sampling=greedy(0))
    assert res.token_ids == []
    assert res.finish_reason == "length"


def test_page_pressure_preemption():
    # tiny pool: 2 concurrent long generations must fight for pages
    eng = make_engine(num_pages=12, max_batch=4, max_seq_len=128, watermark_pages=1)
    results = {}
    for i in range(3):
        eng.submit(
            list(range(10 + i * 3, 40 + i * 3)),  # 30 tokens → 2 pages each
            greedy(40),
            on_output=lambda o, i=i: results.setdefault(i, []).append(o),
        )
    for _ in range(500):
        eng.step()
        if len([k for k, v in results.items() if v and v[-1].finished]) == 3:
            break
    assert all(results[i][-1].finished for i in range(3)), (
        f"unfinished under page pressure; loads={eng.loads()}, "
        f"preemptions={eng.scheduler.num_preemptions}"
    )
    for i in range(3):
        total = sum(len(o.new_token_ids) for o in results[i])
        assert total == 40


def test_ensure_seq_capacity_refuses_preempted_request():
    """A request evicted as a peer's preemption victim earlier in the same
    decode pass has slot=None; _ensure_seq_capacity must refuse it instead
    of numpy-broadcasting a page id over the whole page table
    (ADVICE r4 medium)."""
    eng = make_engine(num_pages=32, max_batch=4)
    eng.submit(list(range(5, 25)), greedy(64), on_output=lambda o: None)
    eng.step()  # prefill: request becomes resident
    sched = eng.scheduler
    victim = next(r for r in sched.slots if r is not None)
    sched._preempt(victim)
    after_preempt = sched.page_tables.copy()
    assert not sched._ensure_seq_capacity(victim, 4)
    # the preempted request must not have touched any OTHER slot's rows
    assert (sched.page_tables == after_preempt).all()
    assert victim.slot is None


def test_loads_reporting(engine):
    loads = engine.loads()
    assert loads["num_running"] == 0
    assert loads["free_pages"] > 0


def test_radix_never_caches_unwritten_final_token(engine):
    """The final sampled token's KV is never written (it is never fed back);
    its page must not enter the radix cache (regression: poisoned prefix)."""
    engine.flush_cache()
    prompt = list(range(100, 170))  # 70 tokens; +10 outputs = exactly 5 pages
    r1 = engine.generate(prompt_ids=prompt, sampling=greedy(10))
    # 81 tokens: the 5th page (holding the unwritten final-token slot) would
    # be matched if it had been inserted
    ext = prompt + r1.token_ids + [55]
    r2 = engine.generate(prompt_ids=ext, sampling=greedy(5))  # warm (radix hit)
    # only 4 pages (64 tokens) may match: the 5th page holds position 79,
    # whose KV was never written
    assert r2.cached_tokens == 64
    engine.flush_cache()
    r3 = engine.generate(prompt_ids=ext, sampling=greedy(5))  # cold
    assert r2.token_ids == r3.token_ids


def test_decode_horizon_matches_single_step():
    """Multi-step decode (lax.scan horizon) must be semantically identical to
    single-step: same tokens, same stops, overshoot discarded."""
    e1 = make_engine()
    e4 = make_engine(decode_horizon=4)
    prompts = [list(range(10, 40)), list(range(50, 75)), list(range(80, 101))]
    for p in prompts:
        r1 = e1.generate(prompt_ids=p, sampling=greedy(9))  # 9 % 4 != 0: mid-horizon length stop
        r4 = e4.generate(prompt_ids=p, sampling=greedy(9))
        assert r1.token_ids == r4.token_ids
        assert r4.finish_reason == "length"
    # stop token mid-horizon
    probe = e1.generate(prompt_ids=prompts[0], sampling=greedy(6))
    stop_tok = probe.token_ids[2]
    sp = SamplingParams(temperature=0.0, max_new_tokens=16, ignore_eos=True,
                        stop_token_ids=[stop_tok])
    ra = e1.generate(prompt_ids=prompts[0], sampling=sp)
    rb = e4.generate(prompt_ids=prompts[0], sampling=sp)
    assert ra.token_ids == rb.token_ids
    assert rb.finish_reason == "stop" and rb.token_ids[-1] == stop_tok
    # prefix cache integrity with horizon overshoot: warm results must equal cold
    ext = prompts[0] + ra.token_ids
    warm = e4.generate(prompt_ids=ext + [7], sampling=greedy(5))
    e4.flush_cache()
    cold = e4.generate(prompt_ids=ext + [7], sampling=greedy(5))
    assert warm.token_ids == cold.token_ids


def test_horizon_stop_string_trims_overshoot_tokens():
    """With decode_horizon > 1, tokens sampled after a stop string in the same
    horizon must not appear in the output (review finding)."""
    e1 = make_engine()
    e4 = make_engine(decode_horizon=4)
    probe = e1.generate(prompt_ids=list(range(60, 75)), sampling=greedy(8))
    stop_word = f"w{probe.token_ids[2]}"
    sp = SamplingParams(temperature=0.0, max_new_tokens=12, ignore_eos=True, stop=[stop_word])
    r1 = e1.generate(prompt_ids=list(range(60, 75)), sampling=sp)
    r4 = e4.generate(prompt_ids=list(range(60, 75)), sampling=sp)
    assert r4.finish_reason == "stop"
    assert r4.token_ids == r1.token_ids, (r1.token_ids, r4.token_ids)
    assert r4.text == r1.text
    assert stop_word not in r4.text


# ---- penalties wired through the decode path ----


def test_frequency_penalty_changes_decode():
    """A huge frequency penalty under greedy decoding forbids repeats: each
    output token can appear at most once (counts update on-device inside the
    decode horizon scan)."""
    eng = make_engine()
    prompt = list(range(40, 60))
    base = eng.generate(
        prompt_ids=prompt,
        sampling=SamplingParams(temperature=0.0, max_new_tokens=12, ignore_eos=True),
    )
    pen = eng.generate(
        prompt_ids=prompt,
        sampling=SamplingParams(
            temperature=0.0, max_new_tokens=12, ignore_eos=True,
            frequency_penalty=100.0,
        ),
    )
    assert len(pen.token_ids) == 12
    assert len(set(pen.token_ids)) == 12, f"repeat under penalty: {pen.token_ids}"
    # sanity: the unpenalized greedy stream is unaffected by the feature flag
    assert len(base.token_ids) == 12


def test_presence_penalty_mixed_batch():
    """Penalized and unpenalized requests coexist in one decode batch; the
    unpenalized request's stream must match a solo run exactly."""
    eng = make_engine()
    prompt_a = list(range(70, 90))
    prompt_b = list(range(90, 110))
    solo = eng.generate(prompt_ids=prompt_a, sampling=greedy(10))

    outs = {}

    def cb(out):
        if out.finished:
            outs[out.rid] = out

    eng.submit(prompt_a, greedy(10), rid="plain", on_output=cb)
    eng.submit(
        prompt_b,
        SamplingParams(
            temperature=0.0, max_new_tokens=10, ignore_eos=True,
            presence_penalty=50.0,
        ),
        rid="penalized",
        on_output=cb,
    )
    import time
    deadline = time.monotonic() + 120
    while len(outs) < 2 and time.monotonic() < deadline:
        eng.step()
    assert set(outs) == {"plain", "penalized"}

    full_plain = []
    # collect all tokens for "plain" by regenerating (callback only kept last)
    again = eng.generate(prompt_ids=prompt_a, sampling=greedy(10))
    assert again.token_ids == solo.token_ids


def test_repetition_penalty_hits_prompt_tokens():
    """repetition_penalty also penalizes prompt tokens (HF semantics): with a
    strong penalty the greedy continuation diverges from the unpenalized one
    whenever the latter re-emits prompt vocabulary."""
    eng = make_engine()
    prompt = [7] * 16  # heavily biased context: greedy likely re-emits 7s
    base = eng.generate(prompt_ids=prompt, sampling=greedy(8))
    pen = eng.generate(
        prompt_ids=prompt,
        sampling=SamplingParams(
            temperature=0.0, max_new_tokens=8, ignore_eos=True,
            repetition_penalty=1e6,
        ),
    )
    assert 7 not in pen.token_ids


def test_plan_cache_auto_size_respects_tp_sharding():
    """Auto-sizing uses PER-DEVICE page bytes: under tp the kv-lane dim is
    sharded, so each device holds 1/tp of every page and the same HBM budget
    fits tp x more pages (VERDICT r1 weak #4: tp=1 was hardcoded and a v5e-8
    would leave most of HBM idle)."""
    from smg_tpu.engine.kv_cache import plan_cache
    from smg_tpu.models.config import tiny_test_config

    model = tiny_test_config()
    cache = CacheConfig(page_size=16, num_pages=4, auto_size=True,
                        hbm_utilization=1.0, dtype="float32")
    budget = 8 * 2**20

    solo = plan_cache(model, cache, hbm_bytes_free=budget, param_bytes=0, tp=1)
    tp2 = plan_cache(model, cache, hbm_bytes_free=budget, param_bytes=0, tp=2)
    # global shape is identical; only the page count scales
    assert tp2.num_kv_heads == model.num_kv_heads == solo.num_kv_heads
    assert tp2.num_pages == 2 * solo.num_pages
    # weights eat into the budget
    heavy = plan_cache(model, cache, hbm_bytes_free=budget,
                       param_bytes=budget // 2, tp=1)
    assert heavy.num_pages < solo.num_pages
    # a tp that doesn't divide the fused kv lanes falls back to unsharded
    odd = plan_cache(model, cache, hbm_bytes_free=budget, param_bytes=0, tp=3)
    assert odd.num_pages == solo.num_pages


def test_engine_auto_size_smoke():
    """auto_size=True end-to-end: the runner sizes from real device stats (or
    falls back to the configured num_pages when the backend has none) and the
    engine still generates."""
    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=True,
                          hbm_utilization=0.05, dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4,
            max_seq_len=64,
            max_prefill_tokens=32,
            prefill_token_buckets=(16, 32),
            decode_batch_buckets=(4,),
        ),
        dtype="float32",
    )
    eng = Engine(cfg, tokenizer=MockTokenizer())
    assert eng.runner.spec.num_pages >= 16
    res = eng.generate(prompt_ids=list(range(5, 15)), sampling=greedy(4))
    assert len(res.token_ids) == 4
