"""Routing decision observability (gateway/route_observability.py): decision
ring bound + schema, predicted-vs-actual reconciliation (incl. a
fault-injected stale kv index via smg_tpu/faults.py), KvEventMonitor health
metrics, and /debug/router + /debug/kv_index end-to-end over in-proc
workers — the gateway-side twin of tests/test_flight_recorder.py."""

import asyncio
import threading
from dataclasses import dataclass

import pytest
from aiohttp.test_utils import TestClient, TestServer
from prometheus_client import generate_latest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.faults import FAULTS
from smg_tpu.gateway.kv_events import KvEventMonitor
from smg_tpu.gateway.observability import Metrics
from smg_tpu.gateway.server import AppContext, build_app
from smg_tpu.gateway.worker_client import InProcWorkerClient, WorkerClient
from smg_tpu.gateway.workers import Worker, WorkerRegistry
from smg_tpu.models.config import tiny_test_config
from smg_tpu.policies import (
    DECISION_KEYS,
    PolicyRegistry,
    RequestContext,
    RouteDecision,
    get_policy,
)
from smg_tpu.protocols.events import BlockStored, KvEventBatch
from smg_tpu.tokenizer import MockTokenizer


@pytest.fixture(autouse=True)
def _disarm_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


@dataclass
class FakeWorker:
    worker_id: str
    model_id: str = "m"
    load: int = 0
    healthy: bool = True

    def is_available(self) -> bool:
        return self.healthy


def fake_workers(n=3):
    return [FakeWorker(worker_id=f"w{i}") for i in range(n)]


# ---------------------------------------------------------------------------
# decision ring
# ---------------------------------------------------------------------------


def test_decision_ring_bounded_under_churn():
    m = Metrics()
    m.route.ring_size = 8
    for i in range(100):
        m.route.record(RouteDecision(policy="round_robin", model_id="m",
                                     chosen=f"w{i % 4}", outcome="round_robin"))
    body = m.route.debug_router()
    assert body["ring_size"] == 8
    assert body["num_decisions"] == 100
    ring = body["models"]["m"]
    assert ring["window"] == 8
    # newest last, serials strictly increasing, oldest 92 dropped
    serials = [d["serial"] for d in ring["decisions"]]
    assert serials == sorted(serials) and serials[-1] == 100 and serials[0] == 93


def test_debug_router_limit_and_model_filter_and_schema():
    m = Metrics()
    for mid in ("a", "b"):
        for _ in range(5):
            m.route.record(RouteDecision(policy="random", model_id=mid,
                                         chosen="w0", outcome="random"))
    body = m.route.debug_router(model="a", limit=2)
    assert set(body["models"]) == {"a"}
    assert len(body["models"]["a"]["decisions"]) == 2
    for rec in body["models"]["a"]["decisions"]:
        assert set(rec) == set(DECISION_KEYS)
    # unknown model: empty but well-formed
    assert m.route.debug_router(model="ghost")["models"]["ghost"]["window"] == 0


def test_decision_ring_counts_by_policy_and_outcome():
    m = Metrics()
    for outcome in ("prefix_hit", "prefix_hit", "below_threshold"):
        m.route.record(RouteDecision(policy="cache_aware", outcome=outcome))
    text = generate_latest(m.registry).decode()
    assert ('smg_route_decisions_total{outcome="prefix_hit",'
            'policy="cache_aware"} 2.0') in text


# ---------------------------------------------------------------------------
# predicted-vs-actual reconciliation
# ---------------------------------------------------------------------------


def test_reconcile_outcomes_error_and_staleness():
    m = Metrics()
    route = m.route

    def dec(predicted):
        d = RouteDecision(policy="cache_aware", model_id="m", chosen="w0",
                          outcome="prefix_hit", predicted_match_tokens=predicted)
        route.record(d)
        return d

    route.reconcile(dec(64), "w0", 64)   # exact
    route.reconcile(dec(128), "w0", 64)  # over: stale index entries
    route.reconcile(dec(0), "w0", 32)    # under: missed events
    body = route.debug_router()
    stats = body["reconciliation"]["w0"]
    assert stats["count"] == 3
    assert (stats["exact"], stats["over"], stats["under"]) == (1, 1, 1)
    assert stats["mean_abs_error_tokens"] == pytest.approx((0 + 64 + 32) / 3)
    assert stats["last_predicted"] == 0 and stats["last_actual"] == 32
    assert body["num_reconciled"] == 3
    text = generate_latest(m.registry).decode()
    assert ('smg_route_reconciliations_total{outcome="over",'
            'worker_id="w0"} 1.0') in text
    # the decision record itself carries the reconciled truth
    d = body["models"]["m"]["decisions"][-1]
    assert d["reconciled"] and d["worker_cached_tokens"] == 32
    assert d["prediction_error_tokens"] == -32


def test_reconcile_is_idempotent_and_skips_no_prediction():
    route = Metrics().route
    d = RouteDecision(policy="cache_aware", chosen="w0",
                      predicted_match_tokens=10)
    route.reconcile(d, "w0", 10)
    route.reconcile(d, "w0", 999)  # second chunk must not double-count
    assert d.worker_cached_tokens == 10
    assert route.debug_router()["reconciliation"]["w0"]["count"] == 1
    no_pred = RouteDecision(policy="manual", chosen="w1")
    route.reconcile(no_pred, "w1", 50)
    assert not no_pred.reconciled
    assert "w1" not in route.debug_router()["reconciliation"]


def test_staleness_ema_sign_tracks_overstatement():
    route = Metrics().route
    for _ in range(10):
        d = RouteDecision(policy="cache_aware", predicted_match_tokens=100)
        route.reconcile(d, "w0", 0)  # index claims cache the worker lost
    stale = route.debug_router()["reconciliation"]["w0"]["staleness"]
    assert stale > 0.5  # positive EMA = gateway index overstates the worker


def test_on_worker_removed_purges_per_worker_state():
    m = Metrics()
    route = m.route
    d = RouteDecision(policy="cache_aware", model_id="m", chosen="w0",
                      outcome="prefix_hit", predicted_match_tokens=8)
    route.record(d)
    route.reconcile(d, "w0", 8)
    assert "w0" in route.debug_router()["reconciliation"]
    # the gateway purges through Policy.on_worker_removed (base behavior)
    p = get_policy("round_robin")
    p._decision_sink = route
    p.on_worker_removed("w0")
    body = route.debug_router()
    assert "w0" not in body["reconciliation"]
    text = generate_latest(m.registry).decode()
    assert 'smg_route_index_staleness{worker_id="w0"}' not in text
    # ring HISTORY keeps the worker — that is the postmortem record
    assert body["models"]["m"]["decisions"][-1]["chosen"] == "w0"


# ---------------------------------------------------------------------------
# KvEventMonitor health metrics + fault-injected stale index
# ---------------------------------------------------------------------------


class _EventClient(WorkerClient):
    """Worker client test double with a controllable kv-event feed."""

    def __init__(self, fail_subscribe=False):
        self.fail_subscribe = fail_subscribe
        self.callback = None

    def subscribe_kv_events(self, callback):
        if self.fail_subscribe:
            raise RuntimeError("event stream unavailable")
        self.callback = callback
        return lambda: None


def _event_gateway(page_size=4):
    registry = WorkerRegistry()
    policies = PolicyRegistry(
        default="cache_aware", mode="event", match_threshold=0.25,
        page_size=page_size, seed=0,
    )
    metrics = Metrics()
    metrics.route.watch(policies)
    monitor = KvEventMonitor(registry, policies, metrics=metrics)
    return registry, policies, metrics, monitor


def _stored_batch(tokens, page_size=4, seq=1):
    from smg_tpu.kv_index.positional import chain_hash

    hashes, parent = [], 0
    for i in range(len(tokens) // page_size):
        parent = chain_hash(parent, tuple(tokens[i * page_size:(i + 1) * page_size]))
        hashes.append(parent)
    return KvEventBatch(sequence_number=seq, events=[
        BlockStored(block_hashes=hashes, token_ids=list(tokens),
                    block_size=page_size),
    ])


def test_kv_subscribe_failure_is_metered():
    registry, _, metrics, monitor = _event_gateway()
    registry.add(Worker(worker_id="w0", client=_EventClient(fail_subscribe=True),
                        model_id="m", page_size=4))
    assert monitor.degraded == {"w0"}
    text = generate_latest(metrics.registry).decode()
    assert 'smg_kv_event_subscribe_failures_total{worker_id="w0"} 1.0' in text
    assert "smg_kv_event_degraded_workers 1.0" in text
    registry.remove("w0")
    assert monitor.degraded == set()
    assert "smg_kv_event_degraded_workers 0.0" in generate_latest(
        metrics.registry).decode()


def test_kv_page_size_mismatch_is_metered():
    registry, _, metrics, monitor = _event_gateway(page_size=4)
    # first worker's page size seeds the indexer; once it holds blocks, a
    # worker that disagrees enters the (previously log-only) degraded mode
    w0 = _EventClient()
    registry.add(Worker(worker_id="w0", client=w0, model_id="m", page_size=4))
    w0.callback(_stored_batch(list(range(8))))
    registry.add(Worker(worker_id="w1", client=_EventClient(),
                        model_id="m", page_size=8))
    assert monitor.degraded == {"w1"}
    assert "smg_kv_event_degraded_workers 1.0" in generate_latest(
        metrics.registry).decode()


def test_fault_injected_stale_index_reconciliation():
    """Armed ``gateway.kv_event`` drops event batches: the gateway index goes
    stale (missing blocks), event-mode matching predicts 0, and reconciling
    the engine-reported cached_tokens surfaces the drift as ``under`` with a
    negative staleness EMA — the exact signature of a lost event feed."""
    registry, policies, metrics, _ = _event_gateway(page_size=4)
    client = _EventClient()
    registry.add(Worker(worker_id="w0", client=client, model_id="m",
                        page_size=4))
    policy = policies.policy_for("m")
    tokens = list(range(16))

    FAULTS.arm("gateway.kv_event", mode="always")
    client.callback(_stored_batch(tokens))  # dropped: index stays empty
    assert policy.indexer.stats()["blocks"] == 0

    w = FakeWorker(worker_id="w0", model_id="m")
    chosen, decision = policy.select(
        [w], RequestContext(model_id="m", token_ids=tokens))
    assert decision.predicted_match_tokens == 0  # stale index sees nothing
    # the engine actually had the prefix cached: reconciliation says "under"
    metrics.route.reconcile(decision, "w0", 16)
    stats = metrics.route.debug_router()["reconciliation"]["w0"]
    assert stats["under"] == 1 and stats["staleness"] < 0

    FAULTS.clear()
    client.callback(_stored_batch(tokens, seq=2))  # feed recovers
    assert policy.indexer.stats()["per_worker_blocks"]["w0"] == 4
    chosen, decision = policy.select(
        [w], RequestContext(model_id="m", token_ids=tokens))
    assert decision.outcome == "prefix_hit"
    assert decision.predicted_match_tokens == 16
    metrics.route.reconcile(decision, "w0", 16)
    assert metrics.route.debug_router()["reconciliation"]["w0"]["exact"] == 1


def test_cache_index_gauges_fold_into_registry():
    """cache_aware tree/indexer stats surface as gauges on the gateway
    registry (satellite: CollectorRegistry fold-in)."""
    registry, policies, metrics, _ = _event_gateway(page_size=4)
    client = _EventClient()
    registry.add(Worker(worker_id="w0", client=client, model_id="m",
                        page_size=4))
    client.callback(_stored_batch(list(range(16))))
    text = generate_latest(metrics.registry).decode()
    assert 'smg_cache_index_blocks{model="m"} 4.0' in text
    assert ('smg_cache_index_worker_blocks{model="m",worker_id="w0"} 4.0'
            in text)
    # approx-mode tree gauges ride the same collector
    tree_policies = PolicyRegistry(default="cache_aware", mode="approx_token")
    m2 = Metrics()
    m2.route.watch(tree_policies)
    p = tree_policies.policy_for(None)
    p.select([FakeWorker("w0")],
             RequestContext(token_ids=list(range(32))))
    text2 = generate_latest(m2.registry).decode()
    assert 'smg_cache_tree_elements{model="__default__"} 32.0' in text2
    assert 'smg_cache_inserted_prefixes{model="__default__"} 1.0' in text2


def test_set_policy_replacement_supersedes_cache_policy_registration():
    """A runtime set_policy replacement must SUPERSEDE the old instance for
    that model key: keeping both would emit duplicate per-model series from
    _CacheIndexCollector (failing the whole /metrics scrape) and leak the
    replaced policy's tree (regression: attach() deduped by identity)."""
    policies = PolicyRegistry(default="cache_aware", mode="approx_token")
    m = Metrics()
    m.route.watch(policies)
    old = policies.policy_for("modelX")
    old.select([FakeWorker("w0")], RequestContext(model_id="modelX",
                                                  token_ids=[1, 2, 3]))
    policies.set_policy("modelX", "cache_aware", mode="approx_token",
                        match_threshold=0.2)
    assert [k for k, _ in m.route.cache_policies()] == ["modelX"]
    assert m.route.cache_policies()[0][1] is not old
    text = generate_latest(m.registry).decode()
    assert text.count('smg_cache_tree_elements{model="modelX"}') == 1
    # a non-cache replacement drops the key from the collector entirely
    policies.set_policy("modelX", "round_robin")
    assert m.route.cache_policies() == []
    assert 'smg_cache_tree_elements{model="modelX"}' not in (
        generate_latest(m.registry).decode()
    )


class _LoadsClient(_EventClient):
    """Event-feed double that also answers the audit's loads() poll."""

    def __init__(self, cached_pages=0):
        super().__init__()
        self.cached_pages = cached_pages

    async def get_loads(self):
        return {"cached_pages": self.cached_pages, "radix_hit_pages": 0}


def test_kv_index_audit_scopes_default_to_unscoped_workers():
    """A worker whose model id maps to its OWN policy instance must not be
    audited against the ``__default__`` policy's indexer: KvEventMonitor
    feeds events to ``policy_for(worker.model_id)``, so pairing the default
    (empty) indexer with another model's worker flags phantom drift in
    multi-model deployments."""
    ctx = AppContext(policy="cache_aware",
                     policy_kwargs={"mode": "event", "page_size": 4, "seed": 0})
    ctx.policies.policy_for(None)  # materialize the __default__ policy
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(60)

    async def _setup():
        ctx.registry.add(Worker(worker_id="w-m2",
                                client=_LoadsClient(cached_pages=500),
                                model_id="m2", page_size=4))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    tc = run(_setup())
    try:
        async def get():
            resp = await tc.get("/debug/kv_index")
            assert resp.status == 200
            return await resp.json()

        body = run(get())
        assert set(body["gateway"]) == {"__default__", "m2"}
        rows = {(a["model"], a["worker_id"]): a for a in body["audit"]}
        # no phantom pairing of m2's worker with the default indexer...
        assert ("__default__", "w-m2") not in rows
        # ...while its own model's entry still reports the real divergence
        m2 = rows[("m2", "w-m2")]
        assert m2["drift_blocks"] == -500 and m2["flagged"]
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)


# ---------------------------------------------------------------------------
# end-to-end: /debug/router + /debug/kv_index over in-proc workers
# ---------------------------------------------------------------------------


def _make_engine() -> Engine:
    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=256, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=8, max_seq_len=256, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(4, 8),
        ),
        dtype="float32",
        model_id="tiny-test",
    )
    return Engine(cfg)


@pytest.fixture(scope="module")
def engine():
    eng = _make_engine()
    yield eng
    eng.stop()


POLICY_CONFIGS = [
    ("round_robin", {}),
    ("cache_aware", {"mode": "approx_token", "match_threshold": 0.05, "seed": 0}),
    ("cache_aware", {"mode": "approx_string", "match_threshold": 0.05, "seed": 0}),
    ("cache_aware", {"mode": "event", "match_threshold": 0.05,
                     "page_size": 16, "seed": 0}),
]


@pytest.mark.parametrize(
    "policy,policy_kwargs", POLICY_CONFIGS,
    ids=[f"{p}-{k.get('mode', 'na')}" for p, k in POLICY_CONFIGS])
def test_debug_router_and_kv_index_end_to_end(engine, policy, policy_kwargs):
    """Acceptance: /debug/router returns bounded, schema-stable decision
    records whose predicted match reconciles against engine-reported
    cached_tokens for cache_aware (all three modes) and round_robin,
    end-to-end over an in-proc worker; /debug/kv_index audits the gateway
    index against worker loads()."""
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()

    def run(coro, timeout=120):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout)

    ctx = AppContext(policy=policy, policy_kwargs=dict(policy_kwargs))
    ctx.tokenizers.register("tiny-test", MockTokenizer(), default=True)

    async def _setup():
        client = InProcWorkerClient(engine)
        ctx.registry.add(Worker(worker_id="w0", client=client,
                                model_id="tiny-test", page_size=16))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    tc = run(_setup())
    try:
        # distinct long prompt per mode (the module-scoped engine's radix
        # cache persists across params), sent twice: the second dispatch
        # reuses the engine-side prefix cache, so cached_tokens > 0 rides
        # its first chunk and reconciliation has real truth to check
        mode = policy_kwargs.get("mode", policy)
        prompt = " ".join(f"w{hash(mode) % 100 + 2}{i} t{i}" for i in range(24))

        async def chat():
            resp = await tc.post("/v1/chat/completions", json={
                "model": "tiny-test",
                "messages": [{"role": "user", "content": prompt}],
                "max_tokens": 2, "temperature": 0, "ignore_eos": True,
            })
            assert resp.status == 200, await resp.text()
            return await resp.json()

        run(chat())
        run(chat())

        async def debug(path):
            resp = await tc.get(path)
            assert resp.status == 200
            return await resp.json()

        body = run(debug("/debug/router?limit=16"))
        assert body["schema_version"] == 1
        ring = body["models"]["tiny-test"]
        assert ring["policy"] == policy
        assert 1 <= ring["window"] <= body["ring_size"]
        for rec in ring["decisions"]:
            assert set(rec) == set(DECISION_KEYS)
            assert rec["policy"] == policy
            assert rec["chosen"] == "w0"
            assert rec["candidates"][0]["worker_id"] == "w0"
            assert rec["decision_us"] > 0
        reconciled = [d for d in ring["decisions"] if d["reconciled"]]
        assert reconciled, "first-chunk cached_tokens never reconciled"
        last = reconciled[-1]
        assert isinstance(last["worker_cached_tokens"], int)
        assert last["predicted_match_tokens"] is not None
        assert (last["prediction_error_tokens"]
                == last["predicted_match_tokens"] - last["worker_cached_tokens"])
        assert body["reconciliation"]["w0"]["count"] >= len(reconciled)
        if policy == "cache_aware":
            # the repeat request must predict reuse — and the engine page
            # rounding bounds the honest error at one page
            assert last["predicted_match_tokens"] > 0
            assert last["mode"] == policy_kwargs["mode"]

        body = run(debug("/debug/kv_index"))
        assert body["schema_version"] == 1
        loads = body["workers"]["w0"]
        assert "cached_pages" in loads and "radix_hit_pages" in loads
        if policy == "cache_aware":
            stats = body["gateway"]["tiny-test"]
            assert stats["mode"] == policy_kwargs["mode"]
            assert stats["indexer"]["page_size"] == 16
            audit = [a for a in body["audit"] if a["worker_id"] == "w0"]
            assert audit and audit[0]["model"] == "tiny-test"
            if policy_kwargs["mode"] == "event":
                assert audit[0]["drift_ratio"] is not None
        else:
            assert body["gateway"] == {}  # no cache index to audit

        # bad query params are a 400, not a 500
        async def bad():
            return (await tc.get("/debug/router?limit=zap")).status
        assert run(bad()) == 400
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)
