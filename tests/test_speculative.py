"""Speculative decoding (prompt-lookup drafting + one-pass verify):
token-identical to plain greedy decode, with measurable draft acceptance on
self-similar contexts."""

import threading

import numpy as np
import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.engine.speculative import SpecConfig, accept_greedy, propose_ngram
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


def test_propose_ngram():
    cfg = SpecConfig(max_draft=4, ngram_max=3, ngram_min=1)
    # suffix [7, 8] occurred earlier, followed by 9, 10, 11
    ids = [1, 7, 8, 9, 10, 11, 2, 7, 8]
    assert propose_ngram(ids, cfg) == [9, 10, 11, 2]
    # nothing repeats
    assert propose_ngram([1, 2, 3, 4], cfg) == []
    # most RECENT earlier occurrence wins
    ids2 = [5, 6, 100, 5, 6, 200, 5, 6]
    assert propose_ngram(ids2, cfg)[0] == 200
    # short contexts don't crash
    assert propose_ngram([3], cfg) == []


def test_accept_greedy():
    # all drafts match: accepted = drafts + bonus
    out, hits = accept_greedy([4, 5, 6], [4, 5, 6, 7])
    assert out == [4, 5, 6, 7] and hits == 3
    # first mismatch replaced by the model's token
    out, hits = accept_greedy([4, 9, 6], [4, 5, 6, 7])
    assert out == [4, 5] and hits == 1
    # immediate mismatch still yields one token
    out, hits = accept_greedy([9], [4, 5])
    assert out == [4] and hits == 0


def _engine(speculative: bool) -> Engine:
    return Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=256, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(2, 4),
            speculative=speculative, spec_max_draft=6,
        ),
        dtype="float32", model_id="tiny-spec",
    ), tokenizer=MockTokenizer())


def _generate(eng, prompt, n=24, temperature=0.0, count_steps=False):
    done = threading.Event()
    acc = []

    def cb(out):
        acc.extend(out.new_token_ids)
        if out.finished:
            done.set()

    eng.submit(prompt, SamplingParams(temperature=temperature,
                                      max_new_tokens=n, ignore_eos=True),
               on_output=cb)
    steps = 0
    for _ in range(500):
        eng.step()
        steps += 1
        if done.is_set():
            return (list(acc), steps) if count_steps else list(acc)
    raise TimeoutError


def test_speculative_matches_plain_greedy():
    """The flagship invariant: greedy output is token-identical with
    speculation on, across repetitive AND novel prompts."""
    plain = _engine(False)
    spec = _engine(True)
    try:
        prompts = [
            [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6],        # highly repetitive
            list(range(40, 70)),                       # novel
            [9, 9, 9, 9, 9, 9, 9],                     # degenerate repeat
            [5, 6] + list(range(80, 100)) + [5, 6],    # distant repeat
        ]
        for p in prompts:
            want = _generate(plain, p)
            got = _generate(spec, p)
            assert got == want, (p, got, want)
    finally:
        plain.stop()
        spec.stop()


def test_speculative_accepts_on_repetitive_context():
    """A model decoding its own earlier pattern accepts drafts — fewer
    engine steps than tokens generated."""
    eng = _engine(True)
    try:
        prompt = [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6]
        ids, steps = _generate(eng, prompt, n=24, count_steps=True)
        assert len(ids) == 24
        assert eng.scheduler.num_spec_drafted > 0
        # the point of speculation: fewer device round trips than tokens
        if eng.scheduler.num_spec_accepted > 0:
            assert steps < 24
    finally:
        eng.stop()


def test_sampling_requests_not_speculated():
    """temperature > 0 stays on the plain path (no spec counters move)."""
    eng = _engine(True)
    try:
        ids = _generate(eng, [5, 6, 7, 5, 6, 7, 5, 6], n=8, temperature=0.8)
        assert len(ids) == 8
        assert eng.scheduler.num_spec_drafted == 0
    finally:
        eng.stop()


def test_speculative_stop_conditions_respected():
    """EOS / max_new_tokens inside an accepted draft run truncate exactly."""
    eng = _engine(True)
    plain = _engine(False)
    try:
        prompt = [5, 6, 7, 5, 6, 7, 5, 6]
        done = threading.Event()
        acc = []

        def cb(out):
            acc.extend(out.new_token_ids)
            if out.finished:
                done.set()

        # small budget: an accepted multi-token draft must clip at 3
        eng.submit(prompt, SamplingParams(temperature=0.0, max_new_tokens=3,
                                          ignore_eos=True), on_output=cb)
        for _ in range(200):
            eng.step()
            if done.is_set():
                break
        want = _generate(plain, prompt, n=3)
        assert acc == want and len(acc) == 3
    finally:
        eng.stop()
        plain.stop()


def test_ngram_index_matches_scan():
    """The incremental index returns the same proposals as the O(window)
    scan on randomized streams (the serving hot path uses the index)."""
    import random

    from smg_tpu.engine.speculative import NgramIndex

    rng = random.Random(0)
    cfg = SpecConfig(max_draft=4, ngram_max=3, ngram_min=1)
    for trial in range(50):
        ids = [rng.randrange(6) for _ in range(rng.randrange(2, 60))]
        idx = NgramIndex(cfg.ngram_min, cfg.ngram_max)
        # grow incrementally like decode does
        stream: list = []
        for chunk in range(0, len(ids), 3):
            stream = ids[: chunk + 3]
            want = propose_ngram(stream, cfg)
            got = propose_ngram(stream, cfg, index=idx)
            assert got == want, (trial, stream, got, want)


def test_ngram_index_survives_rollback():
    """A stop-string-style trim rewrites the tail: the index detects the
    content change and rebuilds instead of proposing from stale positions."""
    from smg_tpu.engine.speculative import NgramIndex

    cfg = SpecConfig(max_draft=4, ngram_max=2, ngram_min=1)
    idx = NgramIndex(1, 2)
    ids = [1, 2, 3, 1, 2, 3, 1, 2]
    assert propose_ngram(ids, cfg, index=idx) == propose_ngram(ids, cfg)
    # trim two tokens and diverge
    ids2 = ids[:-2] + [9, 8, 9]
    assert propose_ngram(ids2, cfg, index=idx) == propose_ngram(ids2, cfg)
    # same length as an earlier state but different content
    ids3 = ids2[:-1] + [7]
    assert propose_ngram(ids3, cfg, index=idx) == propose_ngram(ids3, cfg)
