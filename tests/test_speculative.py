"""Speculative decoding (prompt-lookup drafting + one-pass verify):
token-identical to plain greedy decode, with measurable draft acceptance on
self-similar contexts."""

import threading

import numpy as np
import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.engine.speculative import SpecConfig, accept_greedy, propose_ngram
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


def test_propose_ngram():
    cfg = SpecConfig(max_draft=4, ngram_max=3, ngram_min=1)
    # suffix [7, 8] occurred earlier, followed by 9, 10, 11
    ids = [1, 7, 8, 9, 10, 11, 2, 7, 8]
    assert propose_ngram(ids, cfg) == [9, 10, 11, 2]
    # nothing repeats
    assert propose_ngram([1, 2, 3, 4], cfg) == []
    # most RECENT earlier occurrence wins
    ids2 = [5, 6, 100, 5, 6, 200, 5, 6]
    assert propose_ngram(ids2, cfg)[0] == 200
    # short contexts don't crash
    assert propose_ngram([3], cfg) == []


def test_accept_greedy():
    # all drafts match: accepted = drafts + bonus
    out, hits = accept_greedy([4, 5, 6], [4, 5, 6, 7])
    assert out == [4, 5, 6, 7] and hits == 3
    # first mismatch replaced by the model's token
    out, hits = accept_greedy([4, 9, 6], [4, 5, 6, 7])
    assert out == [4, 5] and hits == 1
    # immediate mismatch still yields one token
    out, hits = accept_greedy([9], [4, 5])
    assert out == [4] and hits == 0


def _engine(speculative: bool) -> Engine:
    return Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=256, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(2, 4),
            speculative=speculative, spec_max_draft=6,
        ),
        dtype="float32", model_id="tiny-spec",
    ), tokenizer=MockTokenizer())


def _generate(eng, prompt, n=24, temperature=0.0, count_steps=False):
    done = threading.Event()
    acc = []

    def cb(out):
        acc.extend(out.new_token_ids)
        if out.finished:
            done.set()

    eng.submit(prompt, SamplingParams(temperature=temperature,
                                      max_new_tokens=n, ignore_eos=True),
               on_output=cb)
    steps = 0
    for _ in range(500):
        eng.step()
        steps += 1
        if done.is_set():
            return (list(acc), steps) if count_steps else list(acc)
    raise TimeoutError


def test_speculative_matches_plain_greedy():
    """The flagship invariant: greedy output is token-identical with
    speculation on, across repetitive AND novel prompts."""
    plain = _engine(False)
    spec = _engine(True)
    try:
        prompts = [
            [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6],        # highly repetitive
            list(range(40, 70)),                       # novel
            [9, 9, 9, 9, 9, 9, 9],                     # degenerate repeat
            [5, 6] + list(range(80, 100)) + [5, 6],    # distant repeat
        ]
        for p in prompts:
            want = _generate(plain, p)
            got = _generate(spec, p)
            assert got == want, (p, got, want)
    finally:
        plain.stop()
        spec.stop()


def test_speculative_accepts_on_repetitive_context():
    """A model decoding its own earlier pattern accepts drafts — fewer
    engine steps than tokens generated."""
    eng = _engine(True)
    try:
        prompt = [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6]
        ids, steps = _generate(eng, prompt, n=24, count_steps=True)
        assert len(ids) == 24
        assert eng.scheduler.num_spec_drafted > 0
        # the point of speculation: fewer device round trips than tokens
        if eng.scheduler.num_spec_accepted > 0:
            assert steps < 24
    finally:
        eng.stop()


def test_sampling_requests_speculated_with_rejection_sampling():
    """temperature > 0 IS speculated since r5: device-side rejection
    sampling accepts drafts distribution-preservingly (VERDICT r4 #4).
    The n-gram proposer rarely fires on novel sampled continuations, so
    the always-proposing draft-model engine carries the assertion."""
    eng = _draft_engine(draft_seed=0)
    try:
        # low temperature: the filtered target distribution is peaked, so
        # the same-weights draft's argmax carries most of the mass and
        # acceptance is near-certain (at high T on a random tiny model the
        # distribution is near-uniform over V=512 and acceptance ~1/V —
        # correct, but nothing to assert on)
        ids = _generate(eng, [5, 6, 7, 5, 6, 7, 5, 6], n=8, temperature=0.05)
        assert len(ids) == 8
        assert eng.scheduler.num_spec_drafted > 0
        assert eng.scheduler.num_spec_accepted > 0
    finally:
        eng.stop()


def test_spec_accept_sample_preserves_distribution():
    """Monte-Carlo check of the rejection-sampling identity: with a
    deterministic draft, the emitted token at the FIRST position must be
    distributed exactly as the target's filtered distribution — the
    accept-or-residual split must not bias it."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from smg_tpu.engine.sampling import _filtered_probs, spec_accept_sample

    V, K = 8, 3
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((K + 1, V)) * 2.0, jnp.float32)
    proposals = jnp.asarray([2, 5, 1], jnp.int32)  # arbitrary fixed drafts
    temp, topk, topp, minp = (
        jnp.float32(0.9), jnp.int32(-1), jnp.float32(1.0), jnp.float32(0.0)
    )
    target = np.asarray(_filtered_probs(logits, temp, topk, topp, minp))[0]

    run = jax.jit(lambda key: spec_accept_sample(
        logits, proposals, jnp.int32(K), key, temp, topk, topp, minp))
    N = 20000
    keys = jax.random.split(jax.random.PRNGKey(42), N)
    finals, n_accs = jax.vmap(run)(keys)
    finals, n_accs = np.asarray(finals), np.asarray(n_accs)
    # first emitted token: proposals[0] when n_acc >= 1 else the residual
    # sample (which IS the final token at position 0)
    first = np.where(n_accs >= 1, int(proposals[0]), finals)
    emp = np.bincount(first, minlength=V) / N
    # ~3 sigma of a multinomial with N=20k: |err| < ~0.012 per bucket
    np.testing.assert_allclose(emp, target, atol=0.015)


def test_spec_accept_sample_respects_top_k():
    """Tokens outside the filtered support can never be emitted."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from smg_tpu.engine.sampling import spec_accept_sample

    V, K = 16, 2
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.standard_normal((K + 1, V)), jnp.float32)
    allowed = {int(x) for row in np.asarray(
        jax.lax.top_k(logits, 2)[1]) for x in row}
    proposals = jnp.asarray([0, 1], jnp.int32)
    run = jax.jit(lambda key: spec_accept_sample(
        logits, proposals, jnp.int32(K), key,
        jnp.float32(1.0), jnp.int32(2), jnp.float32(1.0), jnp.float32(0.0)))
    keys = jax.random.split(jax.random.PRNGKey(7), 512)
    finals, _ = jax.vmap(run)(keys)
    assert set(np.asarray(finals).tolist()) <= allowed


# ---- draft-model proposer ----


def _draft_engine(draft_seed: int) -> Engine:
    return Engine(EngineConfig(
        model=tiny_test_config(),
        draft_model=tiny_test_config(),  # same arch: tiny (tests only)
        draft_seed=draft_seed,
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=256, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(2, 4),
            speculative=True, spec_max_draft=4,
        ),
        dtype="float32", model_id="tiny-spec-draft",
    ), tokenizer=MockTokenizer())


def test_draft_model_greedy_parity_and_acceptance():
    """Draft == target (same init seed): proposals are the target's own
    argmaxes, so acceptance is total — far fewer steps than tokens — and
    output is token-identical to plain greedy."""
    plain = _engine(False)
    spec = _draft_engine(draft_seed=0)  # == EngineConfig.seed -> same params
    try:
        prompt = list(range(40, 60))
        want = _generate(plain, prompt)
        got, steps = _generate(spec, prompt, count_steps=True)
        assert got == want
        assert spec.scheduler.num_spec_accepted > 0
        assert steps < 24
    finally:
        plain.stop()
        spec.stop()


def test_draft_model_mismatched_weights_still_exact():
    """A BAD draft (different weights) must not change greedy output —
    verify gates every token."""
    plain = _engine(False)
    spec = _draft_engine(draft_seed=1234)
    try:
        for prompt in ([5, 6, 7, 5, 6, 7, 5, 6], list(range(70, 95))):
            want = _generate(plain, prompt, n=16)
            got = _generate(spec, prompt, n=16)
            assert got == want
    finally:
        plain.stop()
        spec.stop()


def test_draft_model_survives_preemption():
    """Preemption resets draft coverage (draft_len) with the pages; the
    re-admitted request re-prefills its draft context and still finishes
    exactly."""
    eng = Engine(EngineConfig(
        model=tiny_test_config(),
        draft_model=tiny_test_config(),
        draft_seed=0,
        cache=CacheConfig(page_size=16, num_pages=12, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(2, 4),
            speculative=True, spec_max_draft=4, watermark_pages=1,
        ),
        dtype="float32", model_id="tiny-spec-preempt",
    ), tokenizer=MockTokenizer())
    try:
        done = {}

        def cb(i, out):
            done.setdefault(i, []).append(out)

        for i in range(3):
            eng.submit(list(range(10 + 3 * i, 40 + 3 * i)),
                       SamplingParams(temperature=0.0, max_new_tokens=40,
                                      ignore_eos=True),
                       on_output=lambda o, i=i: cb(i, o))
        for _ in range(600):
            eng.step()
            if len([k for k, v in done.items() if v and v[-1].finished]) == 3:
                break
        for i in range(3):
            assert sum(len(o.new_token_ids) for o in done[i]) == 40
    finally:
        eng.stop()


def test_speculative_stop_conditions_respected():
    """EOS / max_new_tokens inside an accepted draft run truncate exactly."""
    eng = _engine(True)
    plain = _engine(False)
    try:
        prompt = [5, 6, 7, 5, 6, 7, 5, 6]
        done = threading.Event()
        acc = []

        def cb(out):
            acc.extend(out.new_token_ids)
            if out.finished:
                done.set()

        # small budget: an accepted multi-token draft must clip at 3
        eng.submit(prompt, SamplingParams(temperature=0.0, max_new_tokens=3,
                                          ignore_eos=True), on_output=cb)
        for _ in range(200):
            eng.step()
            if done.is_set():
                break
        want = _generate(plain, prompt, n=3)
        assert acc == want and len(acc) == 3
    finally:
        eng.stop()
        plain.stop()


def test_ngram_index_matches_scan():
    """The incremental index returns the same proposals as the O(window)
    scan on randomized streams (the serving hot path uses the index)."""
    import random

    from smg_tpu.engine.speculative import NgramIndex

    rng = random.Random(0)
    cfg = SpecConfig(max_draft=4, ngram_max=3, ngram_min=1)
    for trial in range(50):
        ids = [rng.randrange(6) for _ in range(rng.randrange(2, 60))]
        idx = NgramIndex(cfg.ngram_min, cfg.ngram_max)
        # grow incrementally like decode does
        stream: list = []
        for chunk in range(0, len(ids), 3):
            stream = ids[: chunk + 3]
            want = propose_ngram(stream, cfg)
            got = propose_ngram(stream, cfg, index=idx)
            assert got == want, (trial, stream, got, want)


def test_ngram_index_survives_rollback():
    """A stop-string-style trim rewrites the tail: the index detects the
    content change and rebuilds instead of proposing from stale positions."""
    from smg_tpu.engine.speculative import NgramIndex

    cfg = SpecConfig(max_draft=4, ngram_max=2, ngram_min=1)
    idx = NgramIndex(1, 2)
    ids = [1, 2, 3, 1, 2, 3, 1, 2]
    assert propose_ngram(ids, cfg, index=idx) == propose_ngram(ids, cfg)
    # trim two tokens and diverge
    ids2 = ids[:-2] + [9, 8, 9]
    assert propose_ngram(ids2, cfg, index=idx) == propose_ngram(ids2, cfg)
    # same length as an earlier state but different content
    ids3 = ids2[:-1] + [7]
    assert propose_ngram(ids3, cfg, index=idx) == propose_ngram(ids3, cfg)
