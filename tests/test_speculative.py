"""Speculative decoding (prompt-lookup drafting + one-pass verify):
token-identical to plain greedy decode, with measurable draft acceptance on
self-similar contexts."""

import threading

import numpy as np
import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.engine.speculative import SpecConfig, accept_greedy, propose_ngram
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


def test_propose_ngram():
    cfg = SpecConfig(max_draft=4, ngram_max=3, ngram_min=1)
    # suffix [7, 8] occurred earlier, followed by 9, 10, 11
    ids = [1, 7, 8, 9, 10, 11, 2, 7, 8]
    assert propose_ngram(ids, cfg) == [9, 10, 11, 2]
    # nothing repeats
    assert propose_ngram([1, 2, 3, 4], cfg) == []
    # most RECENT earlier occurrence wins
    ids2 = [5, 6, 100, 5, 6, 200, 5, 6]
    assert propose_ngram(ids2, cfg)[0] == 200
    # short contexts don't crash
    assert propose_ngram([3], cfg) == []


def test_accept_greedy():
    # all drafts match: accepted = drafts + bonus
    out, hits = accept_greedy([4, 5, 6], [4, 5, 6, 7])
    assert out == [4, 5, 6, 7] and hits == 3
    # first mismatch replaced by the model's token
    out, hits = accept_greedy([4, 9, 6], [4, 5, 6, 7])
    assert out == [4, 5] and hits == 1
    # immediate mismatch still yields one token
    out, hits = accept_greedy([9], [4, 5])
    assert out == [4] and hits == 0


def _engine(speculative: bool) -> Engine:
    return Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=256, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(2, 4),
            speculative=speculative, spec_max_draft=6,
        ),
        dtype="float32", model_id="tiny-spec",
    ), tokenizer=MockTokenizer())


def _generate(eng, prompt, n=24, temperature=0.0, count_steps=False):
    done = threading.Event()
    acc = []

    def cb(out):
        acc.extend(out.new_token_ids)
        if out.finished:
            done.set()

    eng.submit(prompt, SamplingParams(temperature=temperature,
                                      max_new_tokens=n, ignore_eos=True),
               on_output=cb)
    steps = 0
    for _ in range(500):
        eng.step()
        steps += 1
        if done.is_set():
            return (list(acc), steps) if count_steps else list(acc)
    raise TimeoutError


def test_speculative_matches_plain_greedy():
    """The flagship invariant: greedy output is token-identical with
    speculation on, across repetitive AND novel prompts."""
    plain = _engine(False)
    spec = _engine(True)
    try:
        prompts = [
            [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6],        # highly repetitive
            list(range(40, 70)),                       # novel
            [9, 9, 9, 9, 9, 9, 9],                     # degenerate repeat
            [5, 6] + list(range(80, 100)) + [5, 6],    # distant repeat
        ]
        for p in prompts:
            want = _generate(plain, p)
            got = _generate(spec, p)
            assert got == want, (p, got, want)
    finally:
        plain.stop()
        spec.stop()


def test_speculative_accepts_on_repetitive_context():
    """A model decoding its own earlier pattern accepts drafts — fewer
    engine steps than tokens generated."""
    eng = _engine(True)
    try:
        prompt = [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6]
        ids, steps = _generate(eng, prompt, n=24, count_steps=True)
        assert len(ids) == 24
        assert eng.scheduler.num_spec_drafted > 0
        # the point of speculation: fewer device round trips than tokens
        if eng.scheduler.num_spec_accepted > 0:
            assert steps < 24
    finally:
        eng.stop()


def test_sampling_requests_speculated_with_rejection_sampling():
    """temperature > 0 IS speculated since r5: device-side rejection
    sampling accepts drafts distribution-preservingly (VERDICT r4 #4).
    The n-gram proposer rarely fires on novel sampled continuations, so
    the always-proposing draft-model engine carries the assertion."""
    eng = _draft_engine(draft_seed=0)
    try:
        # low temperature: the filtered target distribution is peaked, so
        # the same-weights draft's argmax carries most of the mass and
        # acceptance is near-certain (at high T on a random tiny model the
        # distribution is near-uniform over V=512 and acceptance ~1/V —
        # correct, but nothing to assert on)
        ids = _generate(eng, [5, 6, 7, 5, 6, 7, 5, 6], n=8, temperature=0.05)
        assert len(ids) == 8
        assert eng.scheduler.num_spec_drafted > 0
        assert eng.scheduler.num_spec_accepted > 0
    finally:
        eng.stop()


def test_spec_accept_sample_preserves_distribution():
    """Monte-Carlo check of the rejection-sampling identity: with a
    deterministic draft, the emitted token at the FIRST position must be
    distributed exactly as the target's filtered distribution — the
    accept-or-residual split must not bias it."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from smg_tpu.engine.sampling import _filtered_probs, spec_accept_sample

    V, K = 8, 3
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((K + 1, V)) * 2.0, jnp.float32)
    proposals = jnp.asarray([2, 5, 1], jnp.int32)  # arbitrary fixed drafts
    temp, topk, topp, minp = (
        jnp.float32(0.9), jnp.int32(-1), jnp.float32(1.0), jnp.float32(0.0)
    )
    target = np.asarray(_filtered_probs(logits, temp, topk, topp, minp))[0]

    run = jax.jit(lambda key: spec_accept_sample(
        logits, proposals, jnp.int32(K), key, temp, topk, topp, minp))
    N = 20000
    keys = jax.random.split(jax.random.PRNGKey(42), N)
    finals, n_accs = jax.vmap(run)(keys)
    finals, n_accs = np.asarray(finals), np.asarray(n_accs)
    # first emitted token: proposals[0] when n_acc >= 1 else the residual
    # sample (which IS the final token at position 0)
    first = np.where(n_accs >= 1, int(proposals[0]), finals)
    emp = np.bincount(first, minlength=V) / N
    # ~3 sigma of a multinomial with N=20k: |err| < ~0.012 per bucket
    np.testing.assert_allclose(emp, target, atol=0.015)


def test_spec_accept_sample_respects_top_k():
    """Tokens outside the filtered support can never be emitted."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from smg_tpu.engine.sampling import spec_accept_sample

    V, K = 16, 2
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.standard_normal((K + 1, V)), jnp.float32)
    allowed = {int(x) for row in np.asarray(
        jax.lax.top_k(logits, 2)[1]) for x in row}
    proposals = jnp.asarray([0, 1], jnp.int32)
    run = jax.jit(lambda key: spec_accept_sample(
        logits, proposals, jnp.int32(K), key,
        jnp.float32(1.0), jnp.int32(2), jnp.float32(1.0), jnp.float32(0.0)))
    keys = jax.random.split(jax.random.PRNGKey(7), 512)
    finals, _ = jax.vmap(run)(keys)
    assert set(np.asarray(finals).tolist()) <= allowed


# ---- draft-model proposer ----


def _draft_engine(draft_seed: int) -> Engine:
    return Engine(EngineConfig(
        model=tiny_test_config(),
        draft_model=tiny_test_config(),  # same arch: tiny (tests only)
        draft_seed=draft_seed,
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=256, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(2, 4),
            speculative=True, spec_max_draft=4,
        ),
        dtype="float32", model_id="tiny-spec-draft",
    ), tokenizer=MockTokenizer())


def test_draft_model_greedy_parity_and_acceptance():
    """Draft == target (same init seed): proposals are the target's own
    argmaxes, so acceptance is total — far fewer steps than tokens — and
    output is token-identical to plain greedy."""
    plain = _engine(False)
    spec = _draft_engine(draft_seed=0)  # == EngineConfig.seed -> same params
    try:
        prompt = list(range(40, 60))
        want = _generate(plain, prompt)
        got, steps = _generate(spec, prompt, count_steps=True)
        assert got == want
        assert spec.scheduler.num_spec_accepted > 0
        assert steps < 24
    finally:
        plain.stop()
        spec.stop()


def test_draft_model_mismatched_weights_still_exact():
    """A BAD draft (different weights) must not change greedy output —
    verify gates every token."""
    plain = _engine(False)
    spec = _draft_engine(draft_seed=1234)
    try:
        for prompt in ([5, 6, 7, 5, 6, 7, 5, 6], list(range(70, 95))):
            want = _generate(plain, prompt, n=16)
            got = _generate(spec, prompt, n=16)
            assert got == want
    finally:
        plain.stop()
        spec.stop()


def test_draft_model_survives_preemption():
    """Preemption resets draft coverage (draft_len) with the pages; the
    re-admitted request re-prefills its draft context and still finishes
    exactly."""
    eng = Engine(EngineConfig(
        model=tiny_test_config(),
        draft_model=tiny_test_config(),
        draft_seed=0,
        cache=CacheConfig(page_size=16, num_pages=12, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(2, 4),
            speculative=True, spec_max_draft=4, watermark_pages=1,
        ),
        dtype="float32", model_id="tiny-spec-preempt",
    ), tokenizer=MockTokenizer())
    try:
        done = {}

        def cb(i, out):
            done.setdefault(i, []).append(out)

        for i in range(3):
            eng.submit(list(range(10 + 3 * i, 40 + 3 * i)),
                       SamplingParams(temperature=0.0, max_new_tokens=40,
                                      ignore_eos=True),
                       on_output=lambda o, i=i: cb(i, o))
        for _ in range(600):
            eng.step()
            if len([k for k, v in done.items() if v and v[-1].finished]) == 3:
                break
        for i in range(3):
            assert sum(len(o.new_token_ids) for o in done[i]) == 40
    finally:
        eng.stop()


def test_speculative_stop_conditions_respected():
    """EOS / max_new_tokens inside an accepted draft run truncate exactly."""
    eng = _engine(True)
    plain = _engine(False)
    try:
        prompt = [5, 6, 7, 5, 6, 7, 5, 6]
        done = threading.Event()
        acc = []

        def cb(out):
            acc.extend(out.new_token_ids)
            if out.finished:
                done.set()

        # small budget: an accepted multi-token draft must clip at 3
        eng.submit(prompt, SamplingParams(temperature=0.0, max_new_tokens=3,
                                          ignore_eos=True), on_output=cb)
        for _ in range(200):
            eng.step()
            if done.is_set():
                break
        want = _generate(plain, prompt, n=3)
        assert acc == want and len(acc) == 3
    finally:
        eng.stop()
        plain.stop()


def test_ngram_index_matches_scan():
    """The incremental index returns the same proposals as the O(window)
    scan on randomized streams (the serving hot path uses the index)."""
    import random

    from smg_tpu.engine.speculative import NgramIndex

    rng = random.Random(0)
    cfg = SpecConfig(max_draft=4, ngram_max=3, ngram_min=1)
    for trial in range(50):
        ids = [rng.randrange(6) for _ in range(rng.randrange(2, 60))]
        idx = NgramIndex(cfg.ngram_min, cfg.ngram_max)
        # grow incrementally like decode does
        stream: list = []
        for chunk in range(0, len(ids), 3):
            stream = ids[: chunk + 3]
            want = propose_ngram(stream, cfg)
            got = propose_ngram(stream, cfg, index=idx)
            assert got == want, (trial, stream, got, want)


def test_ngram_index_survives_rollback():
    """A stop-string-style trim rewrites the tail: the index detects the
    content change and rebuilds instead of proposing from stale positions."""
    from smg_tpu.engine.speculative import NgramIndex

    cfg = SpecConfig(max_draft=4, ngram_max=2, ngram_min=1)
    idx = NgramIndex(1, 2)
    ids = [1, 2, 3, 1, 2, 3, 1, 2]
    assert propose_ngram(ids, cfg, index=idx) == propose_ngram(ids, cfg)
    # trim two tokens and diverge
    ids2 = ids[:-2] + [9, 8, 9]
    assert propose_ngram(ids2, cfg, index=idx) == propose_ngram(ids2, cfg)
    # same length as an earlier state but different content
    ids3 = ids2[:-1] + [7]
    assert propose_ngram(ids3, cfg, index=idx) == propose_ngram(ids3, cfg)


# ---- fused batched verify: the megastep-integrated spec path (PR 11) ----
#
# Speculation no longer forces sync + K=1: all eligible lanes verify in ONE
# fused device block with on-device acceptance, the verify frame pipelines
# across steps under the overlapped schedule, and rejected columns' KV masks
# to the garbage page.  The invariants pinned here: temp-0 byte-parity vs
# non-spec across overlap modes, overlap-on/off byte-parity at temp 0.8,
# exact mid-stream rejection handling, quarantine rewind of an in-flight
# spec frame, and a 0-recompile / transfer-guard-clean steady state.

import pytest

from smg_tpu.faults import FAULTS
from tests.test_megastep import assert_stream_parity
from tests.test_overlap import greedy, make_engine, run_streams

REP = [5, 6, 7, 8] * 8


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    FAULTS.clear()


def test_spec_temp0_parity_vs_nonspec_overlap_matrix():
    """Acceptance bar: spec-enabled temp-0 streams byte-identical to
    non-spec, for overlap ON and OFF (the engine-gate fingerprint's unit
    -test twin)."""
    jobs = [
        ("r0", REP, greedy(16)),
        ("r1", [9] * 8, greedy(12)),
        ("n0", list(range(40, 70)), greedy(10)),   # novel: drafts mostly miss
        ("d0", [5, 6] + list(range(80, 100)) + [5, 6], greedy(8)),
    ]
    base = run_streams(make_engine(True), jobs)
    for overlap in (True, False):
        eng = make_engine(overlap, speculative=True, spec_max_draft=6)
        got = run_streams(eng, jobs)
        # tokens/text/finish exact; logprobs within 1e-3 (the verify block
        # and the plain decode are different XLA programs — same tolerance
        # as the megastep K-sweep)
        assert_stream_parity(got, base, f"spec overlap={overlap} vs non-spec")
        assert eng.scheduler.num_spec_drafted > 0


def test_spec_temp08_overlap_on_off_parity():
    """At temperature > 0 the rejection-sampled stream is not comparable to
    non-spec, but overlap on/off WITH spec must stay byte-identical — the
    pipelined verify frame consumes exactly the sync schedule's key folds."""
    jobs = [
        ("s0", REP, SamplingParams(temperature=0.8, top_k=40,
                                   max_new_tokens=12, ignore_eos=True)),
        ("s1", [11, 12, 13] * 9,
         SamplingParams(temperature=0.8, min_p=0.02, max_new_tokens=9,
                        ignore_eos=True)),
        ("g0", [9] * 10, greedy(10)),
    ]
    on = run_streams(make_engine(True, speculative=True, spec_max_draft=6),
                     jobs)
    off = run_streams(make_engine(False, speculative=True, spec_max_draft=6),
                      jobs)
    assert on == off, "pipelined spec diverged from sync spec at temp 0.8"


def test_spec_mid_stream_rejection_exact():
    """A context whose repetition BREAKS forces mid-block rejections: the
    correction token must land exactly where the non-spec stream puts it,
    and the rejected columns must never surface."""
    jobs = [
        # repeats then diverges (the n-gram drafter keeps proposing the old
        # continuation; the verify must reject it mid-block)
        ("m0", [5, 6, 7, 8] * 4 + [5, 6, 7, 9, 5, 6, 7], greedy(18)),
        ("m1", list(range(40, 64)) + [5, 6, 5, 6, 5, 7], greedy(14)),
    ]
    base = run_streams(make_engine(True), jobs)
    eng = make_engine(True, speculative=True, spec_max_draft=6)
    got = run_streams(eng, jobs)
    assert_stream_parity(got, base, "mid-stream rejection")
    sched = eng.scheduler
    assert sched.num_spec_drafted > 0
    # drafts really missed somewhere (and really hit somewhere): the whole
    # point of the scenario
    assert 0 < sched.num_spec_accepted < sched.num_spec_drafted


def test_spec_quarantine_rewind_survivor_parity():
    """A poison decode launch with a spec frame in flight: blame lands on
    the newest lane, the stashed frame's sampling-key fold is rewound before
    the retry refolds, and survivor streams stay byte-identical between the
    pipelined and sync spec schedules at temp 0.8 — key-sensitive."""

    def run(overlap: bool) -> dict:
        eng = make_engine(overlap, speculative=True, spec_max_draft=4)
        jobs = [
            (f"q{i}", [5 + i, 6 + i, 7 + i, 8 + i] * 6,
             SamplingParams(temperature=0.8, top_k=50, max_new_tokens=8,
                            ignore_eos=True))
            for i in range(3)
        ]
        chunks: dict = {rid: [] for rid, _, _ in jobs}
        for rid, prompt, sp in jobs:
            eng.submit(prompt, sp, rid=rid,
                       on_output=lambda o, rid=rid: chunks[rid].append(o))
        eng.step()  # admit + prefill all three
        FAULTS.arm("engine.decode_step", mode="once")
        for _ in range(300):
            if all(v and v[-1].finished for v in chunks.values()):
                break
            eng.step()
        while eng.scheduler.has_work():
            eng.step()
        FAULTS.clear()
        assert eng.scheduler.num_quarantined == 1
        assert eng.scheduler.inflight is None
        return {
            rid: ([t for o in v for t in o.new_token_ids],
                  v[-1].finish_reason)
            for rid, v in chunks.items()
        }

    piped, sync = run(True), run(False)
    assert piped["q2"][1] == "error" and sync["q2"][1] == "error"
    for rid in ("q0", "q1"):
        assert piped[rid] == sync[rid], f"survivor {rid} diverged"


def test_spec_steady_state_guard_clean():
    """Steady-state decode WITH speculation on: 0 recompiles and no implicit
    transfers across guarded steps (drafting is pure host work, the verify
    launch uploads explicitly, and per-lane draft counts ride device
    scalars so variable drafting never retraces)."""
    from smg_tpu.analysis.runtime_guards import steady_state_guard

    eng = make_engine(True, speculative=True, spec_max_draft=4)
    # warm BOTH decode paths at the steady-state shapes: a novel prompt
    # exercises the no-draft megastep fallback, a repetitive one the fused
    # verify block
    run_streams(eng, [("w0", list(range(30, 46)), greedy(6))])
    run_streams(eng, [("w1", REP[:16], greedy(8))])
    done: list = []
    eng.submit(REP[:16], greedy(48), rid="g",
               on_output=lambda o: done.append(o.finished))
    for _ in range(4):  # prime the pipeline
        eng.step()
    with steady_state_guard() as cc:
        for _ in range(6):
            eng.step()
    assert cc.count == 0, "speculative steady state recompiled"
    while eng.scheduler.has_work():
        eng.step()
    assert done and done[-1]
    assert eng.scheduler.num_spec_accepted > 0


def test_spec_frame_ring_and_tier_metrics():
    """Telemetry: the flight-recorder step ring carries spec_drafted/
    spec_accepted (schema v3) and /metrics exposes the tier-labeled
    families."""
    from prometheus_client import generate_latest

    eng = make_engine(True, speculative=True, spec_max_draft=6)
    run_streams(eng, [("f0", REP, greedy(16))])
    ring = eng.dump_flight()["ring"]
    assert all("spec_drafted" in r and "spec_accepted" in r for r in ring)
    assert any(r["spec_drafted"] > 0 for r in ring)
    assert any(r["spec_accepted"] > 0 for r in ring)
    text = generate_latest(eng.metrics.registry).decode()
    assert 'smg_engine_spec_drafted_tokens_total{tier="ngram"}' in text
    assert 'smg_engine_spec_accepted_tokens_total{tier="ngram"}' in text
    assert "smg_engine_spec_accepted_length_count" in text


def test_spec_composes_with_chunked_prefill_admissions():
    """A multi-chunk prompt admits under the per-step budget while spec
    frames fly: resumable chunks stay fold-free, the final sampling chunk
    orders its fold before the next verify launch — streams match the sync
    spec schedule exactly."""
    jobs = [
        ("long", list(range(5, 185)),
         SamplingParams(temperature=0.8, top_k=40, max_new_tokens=8,
                        ignore_eos=True)),
        ("rep", REP, greedy(14)),
        ("c1", [11, 12, 13] * 8,
         SamplingParams(temperature=0.8, max_new_tokens=10, ignore_eos=True)),
    ]
    on = run_streams(make_engine(True, speculative=True, spec_max_draft=4),
                     jobs)
    off = run_streams(make_engine(False, speculative=True, spec_max_draft=4),
                      jobs)
    assert on == off


def test_spec_stop_string_lane_keeps_k1_path():
    """Stop-string lanes are spec-INELIGIBLE (engine-layer matches roll back
    mid-block emissions) and ride the rest-batch megastep at K=1 — streams
    still match non-spec exactly at temp 0."""
    probe = run_streams(
        make_engine(False), [("p", REP, greedy(10))]
    )["p"][0]
    stop_word = f"w{probe[4]}"
    jobs = [
        ("st", REP,
         SamplingParams(temperature=0.0, max_new_tokens=16, ignore_eos=True,
                        stop=[stop_word])),
        ("rep", [9] * 10, greedy(12)),
    ]
    base = run_streams(make_engine(True), jobs)
    got = run_streams(make_engine(True, speculative=True, spec_max_draft=6),
                      jobs)
    assert_stream_parity(got, base, "stop-string lane with spec on")
    assert got["st"][2] == "stop"


def test_spec_tier_and_flag_plumbing():
    """--speculative-tier / --spec-max-draft-tokens reach SchedulerConfig;
    tier 'draft' without a draft model is a validation error."""
    from smg_tpu.cli import build_parser
    from smg_tpu.config.validation import validate_cli_args

    args = build_parser().parse_args([
        "worker", "--model-preset", "tiny", "--speculative",
        "--speculative-tier", "ngram", "--spec-max-draft-tokens", "5",
    ])
    assert not [i for i in validate_cli_args(args) if i.severity == "error"]
    assert args.speculative_tier == "ngram" and args.spec_max_draft == 5

    bad = build_parser().parse_args([
        "worker", "--model-preset", "tiny", "--speculative",
        "--speculative-tier", "draft",
    ])
    assert [i for i in validate_cli_args(bad) if i.severity == "error"]

    with pytest.raises(ValueError):
        SchedulerConfig(speculative_tier="bogus")
    with pytest.raises(ValueError):
        SchedulerConfig(spec_max_draft=0)

    # engine-level resolution: ngram pin beats an installed draft model
    eng = _draft_engine(draft_seed=0)
    try:
        assert eng.scheduler._spec_tier() == "draft"
        import dataclasses

        eng.scheduler.sched = dataclasses.replace(
            eng.scheduler.sched, speculative_tier="ngram"
        )
        assert eng.scheduler._spec_tier() == "ngram"
    finally:
        eng.stop()


def test_launch_wires_spec_tier_flag():
    from smg_tpu.cli import build_parser
    from smg_tpu.gateway.launch import build_engine_from_args

    args = build_parser().parse_args([
        "worker", "--model-preset", "tiny", "--dtype", "float32",
        "--max-batch-size", "4", "--max-seq-len", "256",
        "--speculative", "--speculative-tier", "ngram",
        "--spec-max-draft-tokens", "6",
    ])
    eng = build_engine_from_args(args)
    try:
        sc = eng.config.scheduler
        assert sc.speculative and sc.speculative_tier == "ngram"
        assert sc.spec_max_draft == 6
    finally:
        eng.stop()
