"""Client SDK (generated from openapi.json) + tokenizer tiers: tiktoken BPE
and the L1 prefix cache (VERDICT r3 next-round #10)."""

import asyncio
import base64
import os
import sys
import threading

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "clients", "python"))


# ---- generated SDK ----


def test_sdk_no_drift():
    """The checked-in client matches a fresh generation from openapi.json."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
    import json

    import gen_client

    spec = json.load(open(os.path.join(os.path.dirname(__file__), "..",
                                       "openapi.json")))
    fresh = gen_client.generate(spec)
    checked_in = open(os.path.join(os.path.dirname(__file__), "..",
                                   "clients", "python", "smg_client.py")).read()
    assert fresh == checked_in, "run scripts/gen_client.py to refresh the SDK"


@pytest.fixture(scope="module")
def live_gateway():
    """Real aiohttp server on a TCP port (the stdlib-urllib SDK needs one)."""
    from aiohttp import web

    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.gateway.server import AppContext, build_app
    from smg_tpu.gateway.worker_client import InProcWorkerClient
    from smg_tpu.gateway.workers import Worker
    from smg_tpu.models.config import tiny_test_config
    from smg_tpu.tokenizer import MockTokenizer

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=300):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    eng = Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=2, max_seq_len=128, max_prefill_tokens=32,
            prefill_token_buckets=(32,), decode_batch_buckets=(2,),
        ),
        dtype="float32", model_id="tiny-sdk",
    ), tokenizer=MockTokenizer())
    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("tiny-sdk", MockTokenizer(), default=True)

    async def _setup():
        ctx.registry.add(Worker(worker_id="w0", client=InProcWorkerClient(eng),
                                model_id="tiny-sdk"))
        runner = web.AppRunner(build_app(ctx))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return runner, site._server.sockets[0].getsockname()[1]

    runner, port = run(_setup())

    class H:
        pass

    h = H()
    h.base_url = f"http://127.0.0.1:{port}"
    yield h
    run(runner.cleanup())
    loop.call_soon_threadsafe(loop.stop)
    eng.stop()


def test_sdk_smoke_against_gateway(live_gateway):
    from smg_client import ApiError, SmgClient

    c = SmgClient(live_gateway.base_url)
    assert c.health()["status"] == "ok"
    models = c.list_models()
    assert models["data"][0]["id"] == "tiny-sdk"
    r = c.chat({
        "model": "tiny-sdk",
        "messages": [{"role": "user", "content": "w5 w6"}],
        "max_tokens": 4, "temperature": 0, "ignore_eos": True,
    })
    assert r["usage"]["completion_tokens"] == 4
    # streaming yields parsed chunks
    chunks = list(c.chat({
        "model": "tiny-sdk",
        "messages": [{"role": "user", "content": "w5"}],
        "max_tokens": 3, "temperature": 0, "ignore_eos": True,
        "stream": True,
    }))
    assert len(chunks) >= 3
    assert all("choices" in ch for ch in chunks)
    # errors surface as ApiError with parsed body
    with pytest.raises(ApiError) as exc:
        c.chat({"model": "tiny-sdk", "messages": "nonsense"})
    assert exc.value.status == 400
    assert c.list_workers()["workers"][0]["worker_id"] == "w0"


# ---- tiktoken BPE ----


TINY_RANKS = {
    b"h": 0, b"e": 1, b"l": 2, b"o": 3, b" ": 4, b"w": 5, b"r": 6, b"d": 7,
    b"he": 8, b"ll": 9, b"llo": 10, b"hello": 11, b" w": 12, b"or": 13,
    b"ord": 14, b"!": 15, b"a": 16, b"b": 17, b"c": 18,
}


@pytest.fixture()
def ranks_file(tmp_path):
    p = tmp_path / "tiny.tiktoken"
    with open(p, "wb") as f:
        for tok, rank in TINY_RANKS.items():
            f.write(base64.b64encode(tok) + b" " + str(rank).encode() + b"\n")
    return str(p)


def test_tiktoken_bpe_merge_order(ranks_file):
    from smg_tpu.tokenizer.tiktoken import TiktokenTokenizer, bpe_merge

    tok = TiktokenTokenizer(ranks_file,
                            special_tokens={"<|endoftext|>": 100})
    # "hello" merges all the way to its own token (rank 11)
    assert bpe_merge(b"hello", tok.ranks) == [11]
    # merge priority: "he" (8) before "ll"? both exist — lowest rank first.
    # "held" -> h e l d: best pair "he"(8); then "he"+"l"? absent; "l"+"d"?
    # absent -> [8, 2, 7]
    assert bpe_merge(b"held", tok.ranks) == [8, 2, 7]
    ids = tok.encode("hello world!")
    assert tok.decode(ids) == "hello world!"


def test_tiktoken_special_tokens_atomic(ranks_file):
    from smg_tpu.tokenizer.tiktoken import TiktokenTokenizer

    tok = TiktokenTokenizer(ranks_file,
                            special_tokens={"<|endoftext|>": 100,
                                            "<|sep|>": 101})
    ids = tok.encode("hello<|sep|>world")
    assert 101 in ids
    i = ids.index(101)
    assert tok.decode(ids[:i]) == "hello"
    assert tok.decode(ids[i + 1:]) == "world"
    # skip_special_tokens drops them on decode
    assert tok.decode(ids) == "helloworld"
    assert tok.decode(ids, skip_special_tokens=False) == "hello<|sep|>world"
    # splice guarantee at the special boundary (the L1 precondition)
    pre, post = "hello<|sep|>", "world"
    assert tok.encode(pre) + tok.encode(post) == tok.encode(pre + post)


def test_tiktoken_unknown_bytes_raise(ranks_file):
    from smg_tpu.tokenizer.tiktoken import TiktokenTokenizer

    tok = TiktokenTokenizer(ranks_file)
    with pytest.raises(ValueError):
        tok.encode("zzz")  # 'z' not in the tiny vocab


# ---- L1 prefix cache ----


class SpecialMock:
    """Mock tokenizer with atomic special 'tokens' (whitespace-separated
    words; any whitespace boundary splices exactly, so special-token
    boundaries — which MockTokenizer-style vocab places after a space —
    satisfy the L1 guarantee)."""

    all_special_tokens = ["<|im_end|>"]

    def __init__(self):
        self.encode_calls = []

    def encode(self, text, add_special_tokens=False):
        self.encode_calls.append(text)
        out = []
        for w in text.split():
            out.append(hash(w) % 1000)
        return out


def test_l1_boundaries():
    from smg_tpu.tokenizer.cache import find_boundaries

    text = "a<|im_end|>b<|im_end|>c"
    ends = find_boundaries(text, ["<|im_end|>"])
    assert ends == [len("a<|im_end|>"), len("a<|im_end|>b<|im_end|>")]
    assert find_boundaries(text, []) == []


def test_l1_hit_path_reuses_prefix():
    from smg_tpu.tokenizer.cache import L1PrefixCache

    tok = SpecialMock()
    l1 = L1PrefixCache(tok.all_special_tokens, min_prefix_chars=4)
    sys_prefix = "system long shared prompt <|im_end|> "
    t1 = sys_prefix + "user question one"
    t2 = sys_prefix + "different user words"
    full1 = tok.encode(t1)
    l1.seed(t1, tok.encode, full_ids=full1)
    hit = l1.lookup(t2)
    assert hit is not None
    prefix_ids, end = hit
    assert end <= len(sys_prefix)  # boundary sits right after <|im_end|>
    spliced = prefix_ids + tok.encode(t2[end:])
    assert spliced == tok.encode(t2)


def test_l1_poison_on_unsafe_tokenizer():
    """A tokenizer whose splice equality fails disables the cache."""
    from smg_tpu.tokenizer.cache import L1PrefixCache

    class Unsafe:
        all_special_tokens = ["<|x|>"]

        def encode(self, text, add_special_tokens=False):
            # length-dependent tokenization: splicing never matches
            return [len(text)]

    tok = Unsafe()
    l1 = L1PrefixCache(tok.all_special_tokens, min_prefix_chars=1)
    text = "aaa<|x|>bbb"
    l1.seed(text, tok.encode, full_ids=tok.encode(text))
    assert not l1.active
    assert l1.lookup(text) is None


def test_registry_l1_integration():
    from smg_tpu.tokenizer.registry import TokenizerRegistry

    tok = SpecialMock()
    reg = TokenizerRegistry()
    reg.register("m", tok, default=True)
    sys_prefix = "shared system prompt <|im_end|> "
    a = reg.encode_cached("m", sys_prefix + "alpha beta")
    # second text shares the prefix: the L1 hit must only encode the suffix
    tok.encode_calls.clear()
    b = reg.encode_cached("m", sys_prefix + "gamma delta epsilon")
    # the encode calls during the cached lookup never include the full text
    joined = [c for c in tok.encode_calls if sys_prefix in c and "gamma" in c]
    assert not joined, tok.encode_calls
    assert b == tok.encode(sys_prefix + "gamma delta epsilon")
    l1 = reg._l1_for(tok)
    assert l1.stats()["hits"] >= 1
