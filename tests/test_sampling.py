"""Sampling: the sort-free TPU path must match the exact full-sort reference
wherever it claims exactness (top_k <= 64, nucleus within 64 candidates)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smg_tpu.engine.sampling import K_CAP, sample_tokens, sample_tokens_exact


def _params(B, temp=1.0, top_k=-1, top_p=1.0, min_p=0.0):
    return (
        jnp.full((B,), temp, jnp.float32),
        jnp.full((B,), top_k, jnp.int32),
        jnp.full((B,), top_p, jnp.float32),
        jnp.full((B,), min_p, jnp.float32),
    )


def test_greedy_matches_argmax():
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (4, 100))
    toks, lps = sample_tokens(logits, key, *_params(4, temp=0.0))
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(jnp.argmax(logits, -1)))
    # logprob is log_softmax of chosen token
    ref = jax.nn.log_softmax(logits, -1)
    np.testing.assert_allclose(
        np.asarray(lps), np.asarray(jnp.max(ref, -1)), rtol=1e-5
    )


@pytest.mark.parametrize("top_k,top_p,min_p", [
    (5, 1.0, 0.0), (1, 1.0, 0.0), (64, 1.0, 0.0),
    (-1, 0.5, 0.0), (-1, 0.9, 0.0), (10, 0.7, 0.0),
    (-1, 1.0, 0.25),
])
def test_fast_masks_match_exact_support(top_k, top_p, min_p):
    """Both implementations must sample from the same support set (exactness
    holds when the nucleus fits in K_CAP candidates, so use peaky logits):
    with a shared gumbel key the masked argmax must coincide."""
    key = jax.random.PRNGKey(42)
    # exponential-decay logits: nucleus of any top_p < 1 fits well inside 64
    base = -0.4 * jnp.arange(512, dtype=jnp.float32)
    perm = jax.random.permutation(key, 512)
    logits = jnp.tile(base[perm][None], (8, 1)) + jax.random.normal(key, (8, 512)) * 0.01
    params = _params(8, 1.0, top_k, top_p, min_p)
    for i in range(5):
        k = jax.random.fold_in(key, i)
        t_fast, _ = sample_tokens(logits, k, *params)
        t_exact, _ = sample_tokens_exact(logits, k, *params)
        np.testing.assert_array_equal(np.asarray(t_fast), np.asarray(t_exact))


def test_top_k_one_is_greedy():
    key = jax.random.PRNGKey(7)
    logits = jax.random.normal(key, (6, 333))
    toks, _ = sample_tokens(logits, key, *_params(6, temp=1.0, top_k=1))
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(jnp.argmax(logits, -1)))


def test_top_p_tiny_keeps_top_token():
    key = jax.random.PRNGKey(9)
    logits = jax.random.normal(key, (6, 200))
    toks, _ = sample_tokens(logits, key, *_params(6, temp=1.0, top_p=1e-6))
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(jnp.argmax(logits, -1)))


def test_sampling_distribution_sane():
    """With temp=1, sampled frequencies should roughly track softmax probs."""
    key = jax.random.PRNGKey(3)
    logits = jnp.tile(jnp.array([[2.0, 1.0, 0.0, -1.0]]), (1, 1))
    probs = np.asarray(jax.nn.softmax(logits[0]))
    counts = np.zeros(4)
    N = 2000
    batched = jnp.tile(logits, (N, 1))
    toks, _ = sample_tokens(batched, key, *_params(N, temp=1.0))
    for t in np.asarray(toks):
        counts[t] += 1
    freq = counts / N
    np.testing.assert_allclose(freq, probs, atol=0.05)


def test_mixed_greedy_and_sampled_rows():
    key = jax.random.PRNGKey(11)
    logits = jax.random.normal(key, (4, 50))
    temps = jnp.array([0.0, 1.0, 0.0, 0.5], jnp.float32)
    toks, _ = sample_tokens(
        logits, key, temps,
        jnp.full((4,), -1, jnp.int32), jnp.ones((4,), jnp.float32), jnp.zeros((4,), jnp.float32),
    )
    am = np.asarray(jnp.argmax(logits, -1))
    t = np.asarray(toks)
    assert t[0] == am[0] and t[2] == am[2]
