"""Multi-device sharding tests on the virtual CPU mesh: TP/DP-sharded
execution must agree with single-device execution exactly (greedy)."""

import jax
import numpy as np
import pytest

from smg_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    ParallelConfig,
    SchedulerConfig,
)
from smg_tpu.engine.engine import Engine
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


def make_engine(parallel: ParallelConfig, devices=None) -> Engine:
    cfg = EngineConfig(
        model=tiny_test_config(),
        parallel=parallel,
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=False, dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4,
            max_seq_len=128,
            max_prefill_tokens=64,
            prefill_token_buckets=(32, 64),
            decode_batch_buckets=(4,),
        ),
        dtype="float32",
    )
    return Engine(cfg, tokenizer=MockTokenizer(), devices=devices)


def greedy(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n, ignore_eos=True)


@pytest.fixture(scope="module")
def single_result(cpu_devices):
    eng = make_engine(ParallelConfig(), devices=cpu_devices[:1])
    return eng.generate(prompt_ids=list(range(5, 30)), sampling=greedy())


def test_tp2_matches_single(cpu_devices, single_result):
    eng = make_engine(ParallelConfig(tp=2), devices=cpu_devices[:2])
    res = eng.generate(prompt_ids=list(range(5, 30)), sampling=greedy())
    assert res.token_ids == single_result.token_ids


def test_tp2_dp2_matches_single(cpu_devices, single_result):
    eng = make_engine(ParallelConfig(dp=2, tp=2), devices=cpu_devices[:4])
    res = eng.generate(prompt_ids=list(range(5, 30)), sampling=greedy())
    assert res.token_ids == single_result.token_ids


def test_train_step_sharded(cpu_devices):
    import jax.numpy as jnp

    from smg_tpu.models import llama
    from smg_tpu.models.config import tiny_test_config
    from smg_tpu.ops.rope import rope_frequencies
    from smg_tpu.parallel.mesh import build_mesh
    from smg_tpu.train import make_train_step

    cfg = tiny_test_config()
    mesh = build_mesh(ParallelConfig(dp=2, tp=2, sp=2), devices=cpu_devices[:8])
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta, None))
    init_fn, step_fn = make_train_step(llama, cfg, inv_freq, mesh)
    state = init_fn(jax.random.PRNGKey(0))
    toks = jnp.ones((4, 32), jnp.int32)
    state, metrics = step_fn(state, toks, toks, jnp.ones((4, 32), jnp.int32))
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["grad_norm"]))
    assert int(state.step) == 1


def test_ep_sharded_moe_matches_single(cpu_devices):
    """Expert-parallel MoE engine is token-exact vs single device."""
    from smg_tpu.models.config import tiny_moe_config
    import dataclasses

    def eng(parallel, devs):
        cfg = EngineConfig(
            model=tiny_moe_config(),
            parallel=parallel,
            cache=CacheConfig(page_size=16, num_pages=64, auto_size=False, dtype="float32"),
            scheduler=SchedulerConfig(
                max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
                prefill_token_buckets=(32, 64), decode_batch_buckets=(4,),
            ),
            dtype="float32",
        )
        return Engine(cfg, tokenizer=MockTokenizer(), devices=devs)

    single = eng(ParallelConfig(), cpu_devices[:1])
    ref = single.generate(prompt_ids=list(range(5, 30)), sampling=greedy())
    ep2 = eng(ParallelConfig(ep=2), cpu_devices[:2])
    res = ep2.generate(prompt_ids=list(range(5, 30)), sampling=greedy())
    assert res.token_ids == ref.token_ids


def test_pp2_train_step_matches_single(cpu_devices):
    """Pipeline-parallel train step (layers sharded over pp, microbatch
    pipeline with ppermute hops) matches the single-device step: same loss
    and same updated params (SURVEY §2.5 PP row)."""
    import jax.numpy as jnp

    from smg_tpu.models import llama
    from smg_tpu.models.config import tiny_test_config
    from smg_tpu.ops.rope import rope_frequencies
    from smg_tpu.parallel.mesh import build_mesh
    from smg_tpu.train import make_train_step

    cfg = tiny_test_config()
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta, None))
    rng = np.random.default_rng(3)
    toks = jnp.asarray(rng.integers(5, cfg.vocab_size - 5, (4, 32)), jnp.int32)
    mask = jnp.ones((4, 32), jnp.int32)

    def run(parallel, devs, **kw):
        mesh = build_mesh(parallel, devices=devs)
        init_fn, step_fn = make_train_step(llama, cfg, inv_freq, mesh, **kw)
        state = init_fn(jax.random.PRNGKey(0))
        state, metrics = step_fn(state, toks, toks, mask)
        return state, metrics

    s1, m1 = run(ParallelConfig(), cpu_devices[:1])
    s2, m2 = run(ParallelConfig(pp=2), cpu_devices[:2], num_microbatches=2)
    assert np.isfinite(float(m2["loss"]))
    np.testing.assert_allclose(float(m2["loss"]), float(m1["loss"]), rtol=2e-5)
    # updated params agree (pipeline backward == dense backward)
    w1 = np.asarray(jax.device_get(s1.params["layers"]["wq"]))
    w2 = np.asarray(jax.device_get(s2.params["layers"]["wq"]))
    np.testing.assert_allclose(w2, w1, rtol=3e-4, atol=3e-6)


def test_pp2_tp2_train_step_runs(cpu_devices):
    """pp x tp composes: manual pp pipeline with GSPMD tp inside the stage."""
    import jax.numpy as jnp

    from smg_tpu.models import llama
    from smg_tpu.models.config import tiny_test_config
    from smg_tpu.ops.rope import rope_frequencies
    from smg_tpu.parallel.mesh import build_mesh
    from smg_tpu.train import make_train_step

    cfg = tiny_test_config()
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta, None))
    mesh = build_mesh(ParallelConfig(pp=2, tp=2), devices=cpu_devices[:4])
    init_fn, step_fn = make_train_step(llama, cfg, inv_freq, mesh,
                                       num_microbatches=2)
    state = init_fn(jax.random.PRNGKey(0))
    toks = jnp.ones((4, 32), jnp.int32)
    state, metrics = step_fn(state, toks, toks, jnp.ones((4, 32), jnp.int32))
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 1
