"""Redis + Postgres storage backends over in-tree wire clients (VERDICT r3
next-round #5).  Both are exercised against in-test protocol servers — a
dict-backed RESP2 server and a sqlite-backed Postgres v3 server with
SCRAM-SHA-256 auth — plus real servers when REDIS_URL / POSTGRES_DSN are set.
"""

import asyncio
import base64
import hashlib
import hmac
import os
import re
import sqlite3
import struct

import pytest

from smg_tpu.storage import ConversationItem, StoredResponse, make_storage
from smg_tpu.storage.pgwire import PgClient, ScramClient, quote_literal
from smg_tpu.storage.redis import RedisStorage
from smg_tpu.storage.resp import RespClient, RespError


# ---- fake RESP2 server (dict/list/zset subset) ----


class FakeRedis:
    def __init__(self):
        self.kv: dict = {}
        self.lists: dict = {}
        self.zsets: dict = {}

    def dispatch(self, args: list[bytes]):
        cmd = args[0].decode().upper()
        a = [x.decode() for x in args[1:]]
        if cmd == "SET":
            self.kv[a[0]] = args[2]
            return "+OK"
        if cmd == "GET":
            v = self.kv.get(a[0])
            return v if v is not None else None
        if cmd == "DEL":
            n = 0
            for k in a:
                n += self.kv.pop(k, None) is not None
                n += self.lists.pop(k, None) is not None
            return n
        if cmd == "ZADD":
            self.zsets.setdefault(a[0], {})[a[2]] = float(a[1])
            return 1
        if cmd == "ZREM":
            return int(self.zsets.get(a[0], {}).pop(a[1], None) is not None)
        if cmd in ("ZRANGE", "ZREVRANGE"):
            members = sorted(self.zsets.get(a[0], {}).items(), key=lambda kv: kv[1])
            if cmd == "ZREVRANGE":
                members = members[::-1]
            lo, hi = int(a[1]), int(a[2])
            hi = len(members) if hi == -1 else hi + 1
            return [m.encode() for m, _ in members[lo:hi]]
        if cmd == "RPUSH":
            self.lists.setdefault(a[0], []).extend(a[1:])
            return len(self.lists[a[0]])
        if cmd == "LRANGE":
            lst = self.lists.get(a[0], [])
            lo, hi = int(a[1]), int(a[2])
            hi = len(lst) if hi == -1 else hi + 1
            return [x.encode() for x in lst[lo:hi]]
        if cmd == "LREM":
            lst = self.lists.get(a[0], [])
            n = lst.count(a[2])
            self.lists[a[0]] = [x for x in lst if x != a[2]]
            return n
        if cmd == "AUTH":
            return "+OK"
        if cmd == "SELECT":
            return "+OK"
        return RespError(f"ERR unknown command {cmd}")

    @staticmethod
    def encode_reply(v) -> bytes:
        if isinstance(v, str) and v.startswith("+"):
            return v.encode() + b"\r\n"
        if isinstance(v, RespError):
            return b"-" + str(v).encode() + b"\r\n"
        if v is None:
            return b"$-1\r\n"
        if isinstance(v, int):
            return b":%d\r\n" % v
        if isinstance(v, bytes):
            return b"$%d\r\n%s\r\n" % (len(v), v)
        if isinstance(v, list):
            return b"*%d\r\n" % len(v) + b"".join(
                FakeRedis.encode_reply(x) for x in v
            )
        raise AssertionError(v)

    async def serve(self, reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                assert line[:1] == b"*"
                n = int(line[1:-2])
                args = []
                for _ in range(n):
                    hdr = await reader.readline()
                    assert hdr[:1] == b"$"
                    ln = int(hdr[1:-2])
                    args.append((await reader.readexactly(ln + 2))[:-2])
                writer.write(self.encode_reply(self.dispatch(args)))
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()


async def _start_fake_redis():
    fake = FakeRedis()
    server = await asyncio.start_server(fake.serve, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    return fake, server, port


# ---- shared storage roundtrip (mirrors test_agentic matrix) ----


async def _roundtrip(s):
    conv = await s.create_conversation({"topic": "x"})
    assert (await s.get_conversation(conv.id)).metadata == {"topic": "x"}
    await s.update_conversation(conv.id, {"y": 1})
    assert (await s.get_conversation(conv.id)).metadata == {"topic": "x", "y": 1}
    assert [c.id for c in await s.list_conversations()] == [conv.id]

    items = [
        ConversationItem(type="message", role="user", content={"content": "hi"}),
        ConversationItem(type="message", role="assistant", content={"content": "y'all"}),
    ]
    await s.add_items(conv.id, items)
    got = await s.list_items(conv.id)
    assert [i.role for i in got] == ["user", "assistant"]
    assert got[1].content == {"content": "y'all"}  # quote-escaping survives
    assert (await s.get_item(conv.id, got[0].id)).id == got[0].id
    assert await s.delete_item(conv.id, got[0].id)
    assert not await s.delete_item(conv.id, got[0].id)
    assert len(await s.list_items(conv.id)) == 1

    r1 = await s.store_response(StoredResponse(model="m", output=[{"type": "message"}]))
    r2 = await s.store_response(StoredResponse(model="m", previous_response_id=r1.id))
    chain = await s.response_chain(r2.id)
    assert [r.id for r in chain] == [r1.id, r2.id]
    assert await s.delete_response(r1.id)
    assert await s.get_conversation("nope") is None
    assert await s.delete_conversation(conv.id)
    assert await s.get_conversation(conv.id) is None
    assert await s.list_items(conv.id) == []


def test_redis_storage_roundtrip_fake_server():
    async def go():
        fake, server, port = await _start_fake_redis()
        s = RedisStorage(client=RespClient("127.0.0.1", port))
        try:
            await _roundtrip(s)
            # all keys cleaned up by the deletes above
            assert not any(k for k in fake.kv if "conv" in k)
        finally:
            await s.close()
            server.close()
            await server.wait_closed()

    asyncio.run(go())


def test_resp_pipeline_and_errors():
    async def go():
        _, server, port = await _start_fake_redis()
        c = RespClient("127.0.0.1", port)
        try:
            replies = await c.pipeline([
                ("SET", "a", "1"), ("GET", "a"), ("BOGUS",), ("GET", "missing"),
            ])
            assert replies[0] == "OK"
            assert replies[1] == b"1"
            assert isinstance(replies[2], RespError)
            assert replies[3] is None
        finally:
            await c.close()
            server.close()
            await server.wait_closed()

    asyncio.run(go())


@pytest.mark.skipif(not os.environ.get("REDIS_URL"), reason="no REDIS_URL")
def test_redis_storage_roundtrip_real_server():
    async def go():
        s = make_storage(os.environ["REDIS_URL"])
        try:
            await _roundtrip(s)
        finally:
            await s.close()

    asyncio.run(go())


# ---- SCRAM-SHA-256 (RFC 7677 test vector) ----


def test_scram_sha256_rfc7677_vector():
    c = ScramClient("user", "pencil", nonce="rOprNGfwEbeRWgbNEkqO")
    first = c.first_message()
    assert first == b"n,,n=user,r=rOprNGfwEbeRWgbNEkqO"
    server_first = (
        b"r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
        b"s=W22ZaJ0SNY7soEsUEjb6gQ==,i=4096"
    )
    final = c.final_message(server_first)
    assert final == (
        b"c=biws,r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
        b"p=dHzbZapWIk4jUhN+Ute9ytag9zjfMHgsqmmiz7AndVQ="
    )
    c.verify_server(b"v=6rriTRBi23WpRR/wtup+mMhUZUn/dB5nLTJRsjl95G4=")


def test_quote_literal():
    assert quote_literal(None) == "NULL"
    assert quote_literal(5) == "5"
    assert quote_literal(True) == "TRUE"
    assert quote_literal("o'brien") == "'o''brien'"
    with pytest.raises(ValueError):
        quote_literal("a\x00b")


# ---- fake Postgres server (sqlite-backed, SCRAM auth) ----


class FakePg:
    """Speaks enough of the v3 protocol to run the storage backend: startup,
    SCRAM-SHA-256 auth (independent implementation from the RFC), simple
    query against an in-memory sqlite with light SQL dialect shims."""

    USER, PASSWORD = "smg", "hunter2"

    def __init__(self):
        self.db = sqlite3.connect(":memory:", check_same_thread=False)
        self.seqs: dict[str, int] = {}

    @staticmethod
    def _msg(kind: bytes, payload: bytes) -> bytes:
        return kind + struct.pack(">I", len(payload) + 4) + payload

    async def serve(self, reader, writer):
        try:
            # startup
            (ln,) = struct.unpack(">I", await reader.readexactly(4))
            await reader.readexactly(ln - 4)
            await self._auth(reader, writer)
            writer.write(self._msg(b"Z", b"I"))
            await writer.drain()
            while True:
                kind = await reader.readexactly(1)
                (ln,) = struct.unpack(">I", await reader.readexactly(4))
                payload = await reader.readexactly(ln - 4)
                if kind == b"X":
                    return
                if kind == b"Q":
                    self._query(payload[:-1].decode(), writer)
                    writer.write(self._msg(b"Z", b"I"))
                    await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    async def _auth(self, reader, writer):
        # request SASL/SCRAM-SHA-256
        writer.write(self._msg(b"R", struct.pack(">I", 10) + b"SCRAM-SHA-256\x00\x00"))
        await writer.drain()
        kind = await reader.readexactly(1)
        (ln,) = struct.unpack(">I", await reader.readexactly(4))
        payload = await reader.readexactly(ln - 4)
        assert kind == b"p"
        mech_end = payload.index(b"\x00")
        assert payload[:mech_end] == b"SCRAM-SHA-256"
        (flen,) = struct.unpack(">I", payload[mech_end + 1:mech_end + 5])
        client_first = payload[mech_end + 5:mech_end + 5 + flen].decode()
        bare = client_first.split(",", 2)[2]
        client_nonce = dict(p.split("=", 1) for p in bare.split(","))["r"]
        # server first
        salt = b"0123456789abcdef"
        iters = 4096
        server_nonce = client_nonce + "SRVNONCE"
        server_first = (
            f"r={server_nonce},s={base64.b64encode(salt).decode()},i={iters}"
        )
        writer.write(self._msg(b"R", struct.pack(">I", 11) + server_first.encode()))
        await writer.drain()
        # client final
        kind = await reader.readexactly(1)
        (ln,) = struct.unpack(">I", await reader.readexactly(4))
        client_final = (await reader.readexactly(ln - 4)).decode()
        without_proof, proof_b64 = client_final.rsplit(",p=", 1)
        salted = hashlib.pbkdf2_hmac("sha256", self.PASSWORD.encode(), salt, iters)
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        auth_msg = ",".join([bare, server_first, without_proof]).encode()
        sig = hmac.new(stored_key, auth_msg, hashlib.sha256).digest()
        want_proof = bytes(a ^ b for a, b in zip(client_key, sig))
        assert base64.b64decode(proof_b64) == want_proof, "bad SCRAM proof"
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        server_sig = hmac.new(server_key, auth_msg, hashlib.sha256).digest()
        verifier = b"v=" + base64.b64encode(server_sig)
        writer.write(self._msg(b"R", struct.pack(">I", 12) + verifier))
        writer.write(self._msg(b"R", struct.pack(">I", 0)))
        await writer.drain()

    def _nextval(self, m: re.Match) -> str:
        # value position only: a nextval('x') INSIDE a quoted string literal
        # (odd number of preceding quotes) is stored content, not SQL
        if m.string.count("'", 0, m.start()) % 2 == 1:
            return m.group(0)
        name = m.group(1)
        self.seqs[name] = self.seqs.get(name, 0) + 1
        return str(self.seqs[name])

    def _query(self, sql: str, writer) -> None:
        # dialect shims: sqlite has no DOUBLE PRECISION/BIGINT distinctions,
        # SET, or sequences — sequences are emulated in self.seqs
        shimmed = (sql.replace("DOUBLE PRECISION", "REAL")
                      .replace("BIGINT", "INTEGER"))
        shimmed = re.sub(r"nextval\('(\w+)'\)", self._nextval, shimmed)
        try:
            cur = self.db.cursor()
            rows = []
            for stmt in [s for s in shimmed.split(";") if s.strip()]:
                s = stmt.strip()
                if s.upper().startswith("SET "):
                    continue
                m = re.match(r"CREATE SEQUENCE IF NOT EXISTS (\w+)", s, re.I)
                if m:
                    self.seqs.setdefault(m.group(1), 0)
                    continue
                m = re.match(r"SELECT setval\('(\w+)',", s, re.I)
                if m:
                    name = m.group(1)
                    cur.execute("SELECT COALESCE(MAX(seq), 0) "
                                "FROM conversation_items")
                    table_max = int(cur.fetchone()[0])
                    self.seqs[name] = max(self.seqs.get(name, 0), table_max)
                    cur.execute(f"SELECT {self.seqs[name]} AS setval")
                    rows = cur.fetchall()
                    continue
                cur.execute(s)
                if cur.description is not None:
                    rows = cur.fetchall()
            self.db.commit()
            if cur.description is not None:
                cols = [d[0] for d in cur.description]
                desc = struct.pack(">H", len(cols))
                for c in cols:
                    desc += c.encode() + b"\x00" + struct.pack(
                        ">IhIhih", 0, 0, 25, -1, -1, 0
                    )
                writer.write(self._msg(b"T", desc))
                for row in rows:
                    data = struct.pack(">H", len(row))
                    for v in row:
                        if v is None:
                            data += struct.pack(">i", -1)
                        else:
                            b = str(v).encode()
                            data += struct.pack(">i", len(b)) + b
                    writer.write(self._msg(b"D", data))
            writer.write(self._msg(b"C", b"OK\x00"))
        except sqlite3.Error as e:
            fields = f"SERROR\x00C42601\x00M{e}\x00\x00".encode()
            writer.write(self._msg(b"E", fields))


async def _start_fake_pg():
    fake = FakePg()
    server = await asyncio.start_server(fake.serve, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    return fake, server, port


def test_postgres_storage_roundtrip_fake_server():
    """Full storage matrix through the real PgClient (SCRAM auth included)
    against the scripted server."""
    from smg_tpu.storage.postgres import PostgresStorage

    async def go():
        _, server, port = await _start_fake_pg()
        client = PgClient("127.0.0.1", port, user=FakePg.USER,
                          password=FakePg.PASSWORD, database="smg")
        s = PostgresStorage(client=client)
        try:
            await _roundtrip(s)
        finally:
            await s.close()
            server.close()
            await server.wait_closed()

    asyncio.run(go())


def test_pg_error_surfaces():
    from smg_tpu.storage.pgwire import PgError

    async def go():
        _, server, port = await _start_fake_pg()
        client = PgClient("127.0.0.1", port, user=FakePg.USER,
                          password=FakePg.PASSWORD, database="smg")
        try:
            with pytest.raises(PgError):
                await client.query("SELECT * FROM no_such_table")
            # the connection survives an error (ReadyForQuery resync)
            rows = await client.query("SELECT 1 AS one")
            assert rows == [{"one": "1"}]
        finally:
            await client.close()
            server.close()
            await server.wait_closed()

    asyncio.run(go())


@pytest.mark.skipif(not os.environ.get("POSTGRES_DSN"), reason="no POSTGRES_DSN")
def test_postgres_storage_roundtrip_real_server():
    async def go():
        s = make_storage(os.environ["POSTGRES_DSN"])
        try:
            await _roundtrip(s)
        finally:
            await s.close()

    asyncio.run(go())


def test_make_storage_schemes():
    from smg_tpu.storage import MemoryStorage, SqliteStorage
    from smg_tpu.storage.postgres import PostgresStorage
    from smg_tpu.storage.redis import RedisStorage as RS

    assert isinstance(make_storage(None), MemoryStorage)
    assert isinstance(make_storage("memory"), MemoryStorage)
    assert isinstance(make_storage("sqlite:"), SqliteStorage)
    assert isinstance(make_storage("redis://h:1/2"), RS)
    assert isinstance(make_storage("postgres://u:p@h/db"), PostgresStorage)
    with pytest.raises(ValueError):
        make_storage("bogus://x")
