"""Plugin host tests: loading, hook ordering, reject/modify actions through
the real HTTP app, and fault isolation (reference: the WASM component host,
``crates/wasm/src/interface/spec.wit`` + ``model_gateway/tests`` wasm tier)."""

import asyncio
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.gateway.server import AppContext, build_app
from smg_tpu.plugins import Continue, Modify, PluginHost, PluginRequest, PluginResponse, Reject


# ---------------------------------------------------------------- unit level

def test_load_from_file(tmp_path):
    p = tmp_path / "plug.py"
    p.write_text(
        "from smg_tpu.plugins import Continue\n"
        "def on_request(req):\n"
        "    return Continue()\n"
    )
    host = PluginHost()
    loaded = host.load(str(p))
    assert loaded.has_on_request and not loaded.has_on_response
    assert len(host.plugins) == 1


def test_load_rejects_hookless_module(tmp_path):
    p = tmp_path / "empty.py"
    p.write_text("x = 1\n")
    with pytest.raises(ValueError, match="exports neither"):
        PluginHost().load(str(p))


def test_on_request_first_reject_wins_and_modify_accumulates(tmp_path):
    host = PluginHost()

    class ModPlug:
        @staticmethod
        def on_request(req):
            return Modify(headers_set={"X-Tag": "a"})

    class RejPlug:
        @staticmethod
        def on_request(req):
            return Reject(403, "nope")

    class NeverPlug:
        @staticmethod
        def on_request(req):
            raise AssertionError("must not run after a reject")

    from smg_tpu.plugins.host import LoadedPlugin

    host.plugins = [
        LoadedPlugin("mod", ModPlug),
        LoadedPlugin("rej", RejPlug),
        LoadedPlugin("never", NeverPlug),
    ]
    req = PluginRequest(method="GET", path="/health")
    action = asyncio.run(host.on_request(req))
    assert isinstance(action, Reject) and action.status == 403
    assert req.headers["x-tag"] == "a"  # modify before the reject still applied


def test_fault_isolation_fail_open_and_closed():
    class Boom:
        @staticmethod
        def on_request(req):
            raise RuntimeError("plugin bug")

    from smg_tpu.plugins.host import LoadedPlugin

    open_host = PluginHost(fail_open=True)
    open_host.plugins = [LoadedPlugin("boom", Boom)]
    action = asyncio.run(open_host.on_request(PluginRequest("GET", "/")))
    assert isinstance(action, Continue)

    closed_host = PluginHost(fail_open=False)
    closed_host.plugins = [LoadedPlugin("boom", Boom)]
    action = asyncio.run(closed_host.on_request(PluginRequest("GET", "/")))
    assert isinstance(action, Reject) and action.status == 500


def test_async_hook_and_timeout():
    class Slow:
        @staticmethod
        async def on_request(req):
            await asyncio.sleep(5)
            return Continue()

    from smg_tpu.plugins.host import LoadedPlugin

    host = PluginHost(fail_open=True, hook_timeout_s=0.05)
    host.plugins = [LoadedPlugin("slow", Slow)]
    action = asyncio.run(host.on_request(PluginRequest("GET", "/")))
    assert isinstance(action, Continue)  # timeout treated as fault, fail-open


def test_on_response_modify():
    class Stamp:
        @staticmethod
        def on_response(resp):
            return Modify(headers_set={"X-Stamped": "yes"}, status=202)

    from smg_tpu.plugins.host import LoadedPlugin

    host = PluginHost()
    host.plugins = [LoadedPlugin("stamp", Stamp)]
    resp = PluginResponse(status=200)
    action = asyncio.run(host.on_response(resp))
    assert isinstance(action, Continue)
    assert resp.status == 202 and resp.headers["x-stamped"] == "yes"


# ------------------------------------------------------------- through HTTP

@pytest.fixture()
def plugin_gateway(tmp_path):
    """App with a reject-by-header plugin and a response-stamping plugin."""
    plug = tmp_path / "guard.py"
    plug.write_text(
        "from smg_tpu.plugins import Continue, Modify, Reject\n"
        "def on_request(req):\n"
        "    if req.headers.get('x-block') == '1':\n"
        "        return Reject(451, 'blocked by guard')\n"
        "    return Continue()\n"
        "def on_response(resp):\n"
        "    return Modify(headers_set={'X-Plugin-Saw': 'true'})\n"
    )
    loop = asyncio.new_event_loop()
    ctx = AppContext(policy="round_robin")
    ctx.load_plugins([str(plug)])

    async def _setup():
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=60)

    tc = run(_setup())
    yield run, tc, ctx
    run(tc.close())
    loop.call_soon_threadsafe(loop.stop)


def test_http_plugin_reject(plugin_gateway):
    run, tc, _ = plugin_gateway

    async def go():
        resp = await tc.get("/health", headers={"X-Block": "1"})
        return resp.status, await resp.json()

    status, body = run(go())
    assert status == 451
    assert body["error"]["type"] == "plugin_rejected"
    assert "blocked by guard" in body["error"]["message"]


def test_http_plugin_passthrough_and_response_modify(plugin_gateway):
    run, tc, _ = plugin_gateway

    async def go():
        resp = await tc.get("/health")
        return resp.status, resp.headers, await resp.json()

    status, headers, body = run(go())
    assert status == 200 and body["status"] == "ok"
    assert headers.get("X-Plugin-Saw") == "true"


def test_http_plugin_fault_does_not_break_gateway(plugin_gateway, tmp_path):
    run, tc, ctx = plugin_gateway
    crash = tmp_path / "crash.py"
    crash.write_text(
        "def on_request(req):\n"
        "    raise RuntimeError('I am a buggy plugin')\n"
    )
    ctx.load_plugins([str(crash)])

    async def go():
        resp = await tc.get("/health")
        return resp.status

    assert run(go()) == 200  # fail-open: buggy plugin logged, request served


def test_cli_flag_wires_plugins(tmp_path):
    """`smg launch --plugins p.py` loads the host before serving."""
    from smg_tpu.cli import build_parser

    plug = tmp_path / "p.py"
    plug.write_text(
        "from smg_tpu.plugins import Continue\n"
        "def on_request(req):\n    return Continue()\n"
    )
    args = build_parser().parse_args(["launch", "--plugins", str(plug)])
    assert args.plugins == [str(plug)]
    ctx = AppContext()
    ctx.load_plugins(args.plugins, fail_open=not args.plugin_fail_closed)
    assert ctx.plugins is not None and len(ctx.plugins.plugins) == 1
