"""HA mesh: CRDT convergence + multi-node gossip in-process
(reference: crates/mesh in-proc multi-node fixtures, SURVEY.md §4)."""

import asyncio

import pytest

from smg_tpu.mesh import GossipConfig, GossipNode, LwwMap


def test_lww_map_merge_converges():
    a = LwwMap("a")
    b = LwwMap("b")
    a.set("w1", {"url": "host1"})
    b.set("w2", {"url": "host2"})
    # cross-merge
    b.merge(a.snapshot())
    a.merge(b.snapshot())
    assert a.items() == b.items() == {"w1": {"url": "host1"}, "w2": {"url": "host2"}}
    # concurrent write on same key: deterministic winner both sides
    a.set("k", "from-a")
    b.set("k", "from-b")
    a.merge(b.snapshot())
    b.merge(a.snapshot())
    assert a.get("k") == b.get("k")
    # delete propagates via tombstone
    a.delete("w1")
    b.merge(a.snapshot())
    assert b.get("w1") is None
    # merge is idempotent
    before = b.items()
    b.merge(a.snapshot())
    assert b.items() == before


def test_lww_change_notifications():
    a = LwwMap("a")
    b = LwwMap("b")
    seen = []
    b.on_change(lambda k, v, d: seen.append((k, v, d)))
    a.set("x", 1)
    a.delete("y")
    b.merge(a.snapshot())
    assert ("x", 1, False) in seen
    assert ("y", None, True) in seen


def test_three_node_gossip_converges():
    async def go():
        n1 = GossipNode(GossipConfig(node_id="n1", interval_secs=60))
        await n1.start()
        n2 = GossipNode(GossipConfig(node_id="n2", seeds=[n1.addr], interval_secs=60))
        await n2.start()
        n3 = GossipNode(GossipConfig(node_id="n3", seeds=[n1.addr], interval_secs=60))
        await n3.start()

        n1.state.set("worker/a", {"url": "10.0.0.1"})
        n3.state.set("worker/c", {"url": "10.0.0.3"})

        # drive rounds deterministically
        for _ in range(12):
            await n1._round()
            await n2._round()
            await n3._round()
        expected = {"worker/a": {"url": "10.0.0.1"}, "worker/c": {"url": "10.0.0.3"}}
        assert n1.state.items() == expected
        assert n2.state.items() == expected
        assert n3.state.items() == expected
        # full membership discovered everywhere
        for n in (n1, n2, n3):
            assert {m.node_id for m in n.alive_members()} == {"n1", "n2", "n3"}

        # failure detection: kill n3, others mark it dead
        await n3.stop()
        n3._server = None
        for _ in range(20):
            await n1._round()
            await n2._round()
        dead = [m for m in n1.members.values() if m.node_id == "n3"]
        assert dead and not dead[0].alive

        await n1.stop()
        await n2.stop()

    asyncio.run(go())


def test_worker_sync_adapter():
    """Two gateways exchange worker registrations through the mesh CRDT."""
    from smg_tpu.gateway.workers import Worker, WorkerRegistry, WorkerType
    from smg_tpu.mesh.adapters import WorkerSyncAdapter

    class FakeClient:
        def __init__(self, url):
            self.url = url

    reg_a, reg_b = WorkerRegistry(), WorkerRegistry()
    state_a, state_b = LwwMap("a"), LwwMap("b")
    WorkerSyncAdapter(reg_a, state_a, client_factory=FakeClient)
    WorkerSyncAdapter(reg_b, state_b, client_factory=FakeClient)

    reg_a.add(Worker(worker_id="w-local", client=FakeClient("u"), model_id="m",
                     worker_type=WorkerType.PREFILL, url="10.0.0.5:30001"))
    # gossip would carry this; simulate one anti-entropy exchange
    state_b.merge(state_a.snapshot())
    synced = reg_b.get("w-local")
    assert synced is not None
    assert synced.url == "10.0.0.5:30001"
    assert synced.worker_type == WorkerType.PREFILL
    # b must NOT republish a remote worker as its own
    assert state_b.get("worker/w-local")["url"] == "10.0.0.5:30001"
    # removal propagates
    reg_a.remove("w-local")
    state_b.merge(state_a.snapshot())
    assert reg_b.get("w-local") is None


def test_tree_sync_replicates_routed_prefixes():
    """A prefix routed on gateway A makes gateway B's cache_aware policy
    route the same prefix to the same worker (reference:
    mesh/adapters/tree_sync.rs, 2-node in-proc)."""
    from smg_tpu.gateway.workers import Worker
    from smg_tpu.gateway.worker_client import WorkerClient
    from smg_tpu.mesh.adapters import TreeSyncAdapter
    from smg_tpu.policies.base import PolicyRegistry, RequestContext

    class FakeClient(WorkerClient):
        pass

    def mk_workers():
        return [
            Worker(worker_id=f"w{i}", client=FakeClient(), model_id="m")
            for i in range(4)
        ]

    state_a, state_b = LwwMap("ga"), LwwMap("gb")
    pol_a = PolicyRegistry(default="cache_aware", seed=1)
    pol_b = PolicyRegistry(default="cache_aware", seed=2)
    TreeSyncAdapter(pol_a, state_a)
    TreeSyncAdapter(pol_b, state_b)

    workers_a, workers_b = mk_workers(), mk_workers()
    prefix = list(range(100, 164))
    ctx = RequestContext(token_ids=prefix)
    chosen = pol_a.policy_for(None).select_worker(workers_a, ctx)
    assert chosen is not None

    # gossip round: B merges A's state
    state_b.merge(state_a.snapshot())

    # B routes the same prefix (plus continuation) to the SAME worker even
    # though its local tree never saw the request
    ctx2 = RequestContext(token_ids=prefix + list(range(164, 180)))
    chosen_b = pol_b.policy_for(None).select_worker(workers_b, ctx2)
    assert chosen_b is not None
    assert chosen_b.worker_id == chosen.worker_id

    # and B's own follow-up inserts replicate back to A
    state_a.merge(state_b.snapshot())
    matches = pol_a.policy_for(None).tree.prefix_match(ctx2.token_ids)
    assert matches.get(chosen.worker_id, 0) >= len(prefix)
