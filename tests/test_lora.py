"""Multi-LoRA serving: adapter bank, per-request adapter selection, PEFT
loading, and the gateway admin surface (reference:
Load/Unload/ListLoRAAdapter RPCs, sglang_scheduler.proto:48-62)."""

import asyncio
import json
import threading

import numpy as np
import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.models.config import tiny_test_config
from smg_tpu.models.lora import empty_adapter, load_peft_dir, validate_adapter
from smg_tpu.protocols.sampling import SamplingParams


def make_engine(**kw) -> Engine:
    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False, dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(4,),
        ),
        dtype="float32",
        **kw,
    )
    return Engine(cfg)


def strong_adapter(cfg, rank=4, seed=0):
    rng = np.random.default_rng(seed)
    w = empty_adapter(cfg, rank)
    for p in ("wq", "wk", "wv", "wo"):
        w[f"{p}_a"] = rng.normal(0, 0.5, w[f"{p}_a"].shape).astype(np.float32)
        w[f"{p}_b"] = rng.normal(0, 0.5, w[f"{p}_b"].shape).astype(np.float32)
    return w


def greedy(max_new=6, **kw) -> SamplingParams:
    return SamplingParams(temperature=0.0, max_new_tokens=max_new, ignore_eos=True, **kw)


@pytest.fixture(scope="module")
def eng():
    return make_engine()


def test_zero_adapter_is_identity(eng):
    prompt = list(range(5, 25))
    base = eng.generate(prompt_ids=prompt, sampling=greedy())
    eng.flush_cache()
    eng.runner.load_lora("zero", empty_adapter(eng.config.model, rank=4))
    z = eng.generate(prompt_ids=prompt, sampling=greedy(lora_adapter="zero"))
    eng.flush_cache()
    assert z.token_ids == base.token_ids


def test_adapter_switching_changes_outputs(eng):
    prompt = list(range(5, 25))
    base = eng.generate(prompt_ids=prompt, sampling=greedy())
    eng.flush_cache()
    eng.runner.load_lora("strong", strong_adapter(eng.config.model))
    s = eng.generate(prompt_ids=prompt, sampling=greedy(lora_adapter="strong"))
    eng.flush_cache()
    assert s.token_ids != base.token_ids
    # switching back to base restores the original stream exactly
    again = eng.generate(prompt_ids=prompt, sampling=greedy())
    eng.flush_cache()
    assert again.token_ids == base.token_ids


def test_mixed_batch_base_stream_exact(eng):
    """Adapted and base requests share one decode batch; the base request's
    stream must match its solo run token for token."""
    prompt_a = list(range(60, 80))
    prompt_b = list(range(80, 100))
    solo = eng.generate(prompt_ids=prompt_a, sampling=greedy(8))
    eng.flush_cache()
    eng.runner.load_lora("strong2", strong_adapter(eng.config.model, seed=7))

    chunks: dict[str, list[int]] = {"plain": [], "adapted": []}
    done = set()

    def mk(rid):
        def cb(o):
            chunks[rid].extend(o.new_token_ids)
            if o.finished:
                done.add(rid)
        return cb

    eng.submit(prompt_a, greedy(8), rid="plain", on_output=mk("plain"))
    eng.submit(prompt_b, greedy(8, lora_adapter="strong2"), rid="adapted",
               on_output=mk("adapted"))
    import time
    deadline = time.monotonic() + 120
    while len(done) < 2 and time.monotonic() < deadline:
        eng.step()
    assert done == {"plain", "adapted"}
    assert chunks["plain"] == solo.token_ids


def test_unknown_adapter_rejected(eng):
    with pytest.raises(ValueError, match="unknown LoRA adapter"):
        eng.submit(list(range(5, 15)), greedy(lora_adapter="nope"))


def test_bank_slot_reuse_and_capacity(eng):
    names_before = set(eng.list_lora_adapters())
    # replacing an existing name reuses its slot
    idx1 = eng.runner.load_lora("zero", empty_adapter(eng.config.model, rank=4))
    idx2 = eng.runner.load_lora("zero", empty_adapter(eng.config.model, rank=4))
    assert idx1 == idx2
    assert set(eng.list_lora_adapters()) == names_before | {"zero"}


def test_peft_dir_loading(tmp_path):
    """HF PEFT layout (adapter_config.json + per-layer lora_A/B tensors)
    converts to the canonical stacked bank layout with alpha/r folded in."""
    cfg = tiny_test_config()
    r, alpha = 2, 8
    E, H, D = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    rng = np.random.default_rng(3)
    tensors = {}
    for layer in range(cfg.num_layers):
        a = rng.normal(0, 1, (r, E)).astype(np.float32)
        b = rng.normal(0, 1, (H * D, r)).astype(np.float32)
        prefix = f"base_model.model.model.layers.{layer}.self_attn.q_proj"
        tensors[f"{prefix}.lora_A.weight"] = a
        tensors[f"{prefix}.lora_B.weight"] = b
    d = tmp_path / "adapter"
    d.mkdir()
    (d / "adapter_config.json").write_text(
        json.dumps({"r": r, "lora_alpha": alpha, "target_modules": ["q_proj"]})
    )
    np.savez(d / "adapter_model.npz", **tensors)

    w = load_peft_dir(str(d), cfg)
    assert validate_adapter(cfg, w) == r
    # A transposed, B transposed and scaled by alpha/r
    a0 = tensors["base_model.model.model.layers.0.self_attn.q_proj.lora_A.weight"]
    b0 = tensors["base_model.model.model.layers.0.self_attn.q_proj.lora_B.weight"]
    np.testing.assert_allclose(w["wq_a"][0], a0.T)
    np.testing.assert_allclose(w["wq_b"][0], b0.T * (alpha / r))
    # untargeted projections stay zero (no-op)
    assert not w["wk_a"].any() and not w["wo_b"].any()


def test_gateway_lora_admin_and_request(tmp_path):
    """Load an adapter through the gateway admin endpoint, generate with and
    without it via /v1/chat/completions, list and unload it."""
    from aiohttp.test_utils import TestClient, TestServer

    from smg_tpu.gateway.server import AppContext, build_app
    from smg_tpu.gateway.worker_client import InProcWorkerClient
    from smg_tpu.gateway.workers import Worker
    from smg_tpu.tokenizer import MockTokenizer

    engine = make_engine(model_id="tiny-test")
    adapter = strong_adapter(engine.config.model, seed=11)
    npz_path = tmp_path / "strong.npz"
    np.savez(npz_path, **adapter)

    loop = asyncio.new_event_loop()
    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("tiny-test", MockTokenizer(), default=True)

    async def go():
        ctx.registry.add(Worker(
            worker_id="w0", client=InProcWorkerClient(engine), model_id="tiny-test",
        ))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        body = {"model": "tiny-test",
                "messages": [{"role": "user", "content": "w5 w6 w7"}],
                "max_tokens": 6, "temperature": 0, "ignore_eos": True}
        base = await (await tc.post("/v1/chat/completions", json=body)).json()

        r = await tc.post("/load_lora_adapter",
                          json={"lora_name": "strong", "lora_path": str(npz_path)})
        load_body = await r.json()

        adapted = await (await tc.post(
            "/v1/chat/completions", json={**body, "lora_adapter": "strong"}
        )).json()
        listed = await (await tc.get("/list_lora_adapters")).json()
        unload = await (await tc.post("/unload_lora_adapter",
                                      json={"lora_name": "strong"})).json()
        missing = await (await tc.post(
            "/v1/chat/completions", json={**body, "lora_adapter": "strong"}
        )).json()
        await tc.close()
        return base, load_body, adapted, listed, unload, missing

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    try:
        base, load_body, adapted, listed, unload, missing = (
            asyncio.run_coroutine_threadsafe(go(), loop).result(timeout=180)
        )
    finally:
        loop.call_soon_threadsafe(loop.stop)
    assert load_body["ok"], load_body
    assert listed["workers"]["w0"] == ["strong"]
    base_text = base["choices"][0]["message"]["content"]
    adapted_text = adapted["choices"][0]["message"]["content"]
    assert adapted_text != base_text, "adapter did not change the output"
    assert unload["ok"], unload
    assert "error" in missing, missing  # unloaded adapter now rejects