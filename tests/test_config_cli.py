"""CLI flag surface + cross-field config validation (VERDICT r4 #7:
~140-flag reference validator parity for the in-tree-meaningful groups,
``config/validation.rs`` analog)."""

import pytest

from smg_tpu.cli import build_parser
from smg_tpu.config.validation import (
    ConfigError,
    raise_on_errors,
    validate_cli_args,
)


def _args(*extra):
    return build_parser().parse_args(["launch", *extra])


def _errors(args):
    return [i for i in validate_cli_args(args) if i.severity == "error"]


def _warns(args):
    return [i for i in validate_cli_args(args) if i.severity == "warn"]


def test_default_launch_args_validate_clean():
    args = _args()
    assert _errors(args) == []


def test_flag_surface_breadth():
    """The reference exposes ~140 flags; the in-tree-meaningful groups must
    be present (spot the group representatives)."""
    args = _args()
    for field in [
        "host", "port", "health_check_port", "policy", "cache_threshold",
        "balance_abs_threshold", "balance_rel_threshold", "max_tree_size",
        "block_size", "prefix_token_count", "dp_aware", "enable_igw",
        "retry_max_retries", "retry_initial_backoff_ms", "retry_max_backoff_ms",
        "disable_retries", "cb_failure_threshold", "cb_success_threshold",
        "cb_timeout_duration_secs", "disable_circuit_breaker",
        "health_check_interval_secs", "health_check_timeout_secs",
        "health_failure_threshold", "health_success_threshold",
        "disable_health_check", "worker_startup_timeout_secs",
        "priority_scheduler_enabled", "priority_slots",
        "rate_limit_tokens_per_second", "rate_limit_burst",
        "api_keys", "jwt_secret", "jwt_jwks_uri", "jwt_issuer", "jwt_audience",
        "trust_tenant_header", "tenant_header_name",
        "service_discovery", "service_discovery_namespace", "selectors",
        "prefill_selectors", "decode_selectors", "service_discovery_port",
        "tls_cert_path", "tls_key_path", "max_payload_size",
        "request_timeout_secs", "cors_allowed_origins", "request_id_headers",
        "harmony", "reasoning_parser", "tool_call_parser", "mcp_config_path",
        "log_json", "prometheus_host", "mesh_port", "mesh_seeds",
        "storage", "otel_endpoint", "kv_connector", "provider_config",
    ]:
        assert hasattr(args, field), f"missing flag field {field}"


def test_serve_engine_flags():
    p = build_parser()
    args = p.parse_args([
        "serve", "--model-preset", "tiny", "--speculative",
        "--draft-model-preset", "tiny", "--tp", "2",
    ])
    assert args.draft_model_preset == "tiny" and args.speculative


# ---- cross-field rules (one test per rule family) ----


def test_tls_needs_both_halves():
    assert any("tls" in str(i) for i in _errors(_args("--tls-cert-path", "/c.pem")))
    assert _errors(_args("--tls-cert-path", "/c.pem", "--tls-key-path", "/k.pem")) == []


def test_probe_port_must_differ():
    bad = _args("--port", "30000", "--health-check-port", "30000")
    assert any("probe port" in i.message for i in _errors(bad))
    ok = _args("--port", "30000", "--health-check-port", "30100")
    assert _errors(ok) == []


def test_retry_backoff_ordering():
    bad = _args("--retry-initial-backoff-ms", "5000",
                "--retry-max-backoff-ms", "1000")
    assert any("backoff" in i.message for i in _errors(bad))


def test_breaker_and_health_thresholds_positive():
    assert _errors(_args("--cb-failure-threshold", "0"))
    assert _errors(_args("--health-success-threshold", "0"))


def test_health_timeout_vs_interval_warns():
    w = _warns(_args("--health-check-timeout-secs", "10",
                     "--health-check-interval-secs", "5"))
    assert any("pile up" in i.message for i in w)


def test_no_retries_no_breaker_warns():
    w = _warns(_args("--disable-retries", "--disable-circuit-breaker"))
    assert any("transient" in i.message for i in w)


def test_cache_threshold_range_and_policy_scope():
    assert _errors(_args("--cache-threshold", "1.5"))
    w = _warns(_args("--policy", "round_robin", "--cache-threshold", "0.7"))
    assert any("ignored by policy" in i.message for i in w)


def test_rate_limit_rules():
    assert _errors(_args("--rate-limit-tokens-per-second", "-1"))
    w = _warns(_args("--rate-limit-tokens-per-second", "100",
                     "--rate-limit-burst", "10"))
    assert any("burst" in i.message for i in w)


def test_api_key_spec_and_jwt_claims():
    assert _errors(_args("--api-key", ":tenant"))
    w = _warns(_args("--jwt-issuer", "https://idp"))
    assert any("jwks" in i.message.lower() for i in w)


def test_trust_tenant_header_without_auth_warns():
    w = _warns(_args("--trust-tenant-header"))
    assert any("redundant" in i.message for i in w)


def test_harmony_overrides_parsers_warns():
    w = _warns(_args("--harmony", "on", "--reasoning-parser", "deepseek_r1"))
    assert any("harmony" in i.message for i in w)


def test_selectors_without_discovery_warn():
    w = _warns(_args("--selector", "app=x"))
    assert any("service-discovery" in i.message for i in w)


def test_draft_model_requires_speculative():
    p = build_parser()
    args = p.parse_args(["serve", "--model-preset", "tiny",
                         "--draft-model-preset", "tiny"])
    assert any("speculative" in i.message for i in _errors(args))


def test_mesh_tls_all_or_nothing():
    bad = _args("--mesh-port", "7946", "--mesh-tls-cert", "/c.pem")
    assert any("mTLS" in i.message for i in _errors(bad))


def test_pd_roles_both_required_still_enforced():
    bad = _args("--prefill-worker", "http://p:1")
    assert any("PD" in i.message for i in _errors(bad))


def test_raise_on_errors_collects_all():
    bad = _args("--tls-cert-path", "/c.pem", "--cb-failure-threshold", "0")
    with pytest.raises(ConfigError) as ei:
        raise_on_errors(validate_cli_args(bad))
    assert len(ei.value.issues) >= 2


def test_dp_aware_default_preserves_min_token():
    """--dp-aware defaults ON: restarting an existing deployment must not
    silently lose min-token DP replica pinning."""
    assert _args().dp_aware is True
    assert _args("--no-dp-aware").dp_aware is False


def test_request_timeout_and_cors_middleware():
    """--request-timeout-secs cuts hung handlers; --cors-allowed-origins
    emits CORS headers + preflight."""
    import asyncio
    import threading

    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from smg_tpu.gateway.server import AppContext, build_app

    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=60)

    ctx = AppContext(policy="round_robin", request_timeout_secs=0.2,
                     cors_allowed_origins=["https://app.example"])

    async def go():
        app = build_app(ctx)

        async def slow(request):
            await asyncio.sleep(5)
            return web.json_response({})

        app.router.add_get("/slow-test", slow)
        tc = TestClient(TestServer(app))
        await tc.start_server()
        r1 = await tc.get("/slow-test")
        out_timeout = (r1.status, (await r1.json())["error"]["type"])
        r2 = await tc.get("/health", headers={"Origin": "https://app.example"})
        cors = r2.headers.get("Access-Control-Allow-Origin")
        r3 = await tc.options("/v1/models",
                              headers={"Origin": "https://app.example"})
        preflight = r3.status
        r4 = await tc.get("/health", headers={"Origin": "https://evil.example"})
        no_cors = r4.headers.get("Access-Control-Allow-Origin")
        await tc.close()
        return out_timeout, cors, preflight, no_cors

    out_timeout, cors, preflight, no_cors = run(go())
    loop.call_soon_threadsafe(loop.stop)
    assert out_timeout == (408, "timeout_error")
    assert cors == "https://app.example"
    assert preflight == 204
    assert no_cors is None


def test_tenant_trust_is_per_context():
    """Tenant-header trust lives on AppContext (not module globals): one
    authed gateway and one open gateway in the same process keep their own
    settings."""
    from smg_tpu.gateway.auth import AuthConfig, Principal
    from smg_tpu.gateway.server import AppContext

    open_ctx = AppContext(policy="round_robin")
    authed = AppContext(
        policy="round_robin",
        auth_config=AuthConfig(enabled=True,
                               api_keys={"k": Principal(id="u", tenant="t1")}),
    )
    assert open_ctx.trust_tenant_header is True
    assert authed.trust_tenant_header is False
    override = AppContext(policy="round_robin", trust_tenant_header=True,
                          auth_config=AuthConfig(enabled=True))
    assert override.trust_tenant_header is True


# ---- tensor-parallel mesh flags (--tensor-parallel-size / --mesh-shape) ----


def _serve(*extra):
    return build_parser().parse_args(
        ["serve", "--model-preset", "tiny", *extra]
    )


def test_tensor_parallel_size_alias():
    """--tensor-parallel-size is the same flag as --tp (reference naming)."""
    assert _serve("--tensor-parallel-size", "4").tp == 4
    assert _serve("--tp", "4").tp == 4


def test_mesh_shape_parses_over_base():
    from smg_tpu.engine.config import ParallelConfig

    p = ParallelConfig.from_spec("dp=2,tp=4")
    assert (p.dp, p.tp, p.sp, p.ep, p.pp) == (2, 4, 1, 1, 1)
    assert p.world_size == 8
    # base values survive for unnamed axes
    p2 = ParallelConfig.from_spec("tp=2", base=ParallelConfig(pp=2))
    assert (p2.tp, p2.pp) == (2, 2)


@pytest.mark.parametrize("bad", ["xx=2", "tp", "tp=zero", "tp=0", "tp=-1",
                                 "tp=2,tp=4"])
def test_mesh_shape_rejects_malformed(bad):
    from smg_tpu.engine.config import ParallelConfig

    with pytest.raises(ValueError):
        ParallelConfig.from_spec(bad)


def test_mesh_shape_flag_conflict_is_error():
    # conflicting axis sizes between --mesh-shape and a per-axis flag
    bad = _serve("--mesh-shape", "tp=4", "--tp", "2")
    assert any("mesh_shape" in i.field for i in _errors(bad))
    # agreement (or the per-axis flag left at its default) is fine
    assert _errors(_serve("--mesh-shape", "tp=4", "--tp", "4")) == []
    assert _errors(_serve("--mesh-shape", "dp=2,tp=4")) == []
    # axes the spec does NOT name merge from the per-axis flags at launch —
    # never a conflict
    assert _errors(_serve("--mesh-shape", "tp=4", "--dp", "2")) == []
    # malformed string surfaces as a startup error, not a trace-time one
    assert any("mesh_shape" in i.field
               for i in _errors(_serve("--mesh-shape", "bogus=2")))
