"""Test bootstrap: force an 8-device CPU jax so the whole engine — including
multi-chip sharding — runs without TPU hardware (SURVEY.md §4 takeaway: mock
workers + CPU-backed engine tests mirror the reference's GPU-free CI tiers).

Must run before the first ``import jax`` anywhere in the test session.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

# Some installs register an always-on TPU plugin that ignores JAX_PLATFORMS;
# pin the default device to CPU so tests never touch real accelerators.
try:
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
except RuntimeError:
    pass


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected >=8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def tiny_cfg():
    from smg_tpu.models.config import tiny_test_config

    return tiny_test_config()
