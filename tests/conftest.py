"""Test bootstrap: force an 8-device CPU jax so the whole engine — including
multi-chip sharding — runs without TPU hardware (SURVEY.md §4 takeaway: mock
workers + CPU-backed engine tests mirror the reference's GPU-free CI tiers).

Self-defending against the ambient remote-TPU PJRT plugin: some installs
register it via sitecustomize at interpreter start (importing jax with
JAX_PLATFORMS=axon already in the env), so merely setting the env var here
is too late.  As long as jax's backends are not yet *initialized*, flipping
the ``jax_platforms`` config narrows backend init to the (local, safe) CPU
client — the same rescue ``__graft_entry__.entry`` uses.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# sitecustomize may have imported jax before this file ran, capturing
# JAX_PLATFORMS=axon; override the live config before any backend spins up.
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

# Belt and braces: pin the default device to CPU so tests never touch real
# accelerators even if a plugin platform slipped through.
try:
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
except RuntimeError:
    pass

# Persistent XLA compilation cache: the suite boots dozens of engines that
# all compile the SAME tiny-model programs (prefill buckets, decode
# megasteps, verify blocks), and XLA compile time dominates tier-1
# wall-clock (ROADMAP practical note — the full suite stopped fitting the
# harness timeout).  Caching compiled executables across engine boots AND
# across runs cuts that cost to one compile per distinct program.
# Parity-safe: a cache hit returns the identical executable.  Override with
# SMG_TEST_COMPILE_CACHE=0 to disable or =<dir> to relocate.
_cache = os.environ.get("SMG_TEST_COMPILE_CACHE", "")
if _cache != "0":
    try:
        import tempfile

        jax.config.update(
            "jax_compilation_cache_dir",
            _cache or os.path.join(tempfile.gettempdir(), "smg-test-xla-cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # older jax without the persistent cache: tests still run


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected >=8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def tiny_cfg():
    from smg_tpu.models.config import tiny_test_config

    return tiny_test_config()
