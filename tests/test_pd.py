"""Prefill/Decode disaggregation: KV handoff between two engines must
reproduce single-engine outputs exactly (reference: PD routing mode +
NIXL/Mooncake connectors, SURVEY.md §2.5)."""

import asyncio
import json
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.gateway.server import AppContext, build_app
from smg_tpu.gateway.worker_client import InProcWorkerClient
from smg_tpu.gateway.workers import Worker, WorkerType
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


def make_engine(model_id="tiny-test", devices=None) -> Engine:
    return Engine(
        EngineConfig(
            model=tiny_test_config(),
            cache=CacheConfig(page_size=16, num_pages=128, auto_size=False, dtype="float32"),
            scheduler=SchedulerConfig(
                max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
                prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(4,),
            ),
            dtype="float32",
            model_id=model_id,
        ),
        devices=devices,
    )


def test_engine_level_kv_handoff():
    """prefill_export on engine A + submit_prefilled on engine B == local
    generation, token for token (greedy)."""
    a = make_engine()
    b = make_engine()
    prompt = list(range(5, 45))  # 40 tokens
    sp = SamplingParams(temperature=0.0, max_new_tokens=8, ignore_eos=True)

    ref = a.generate(prompt_ids=prompt, sampling=sp)
    a.flush_cache()

    export = a.prefill_export(prompt, sp)
    assert export["first_token"] == ref.token_ids[0]
    assert export["seq_len"] == 40
    assert export["k"].shape[1] == 3  # ceil(40/16) pages

    outs = []
    done = threading.Event()

    def cb(o):
        outs.append(o)
        if o.finished:
            done.set()

    b.submit_prefilled(prompt, export["first_token"], export["k"], export["v"], sp,
                       on_output=cb)
    deadline = 300
    while not done.is_set() and deadline:
        b.step()
        deadline -= 1
    tokens = [t for o in outs for t in o.new_token_ids]
    assert tokens == ref.token_ids, (tokens, ref.token_ids)
    # the decode engine never prefilled the prompt
    assert b.scheduler.num_prefill_tokens == 0
    a.stop(); b.stop()


@pytest.fixture(scope="module", params=["auto", "host"])
def pd_gateway(request):
    """PD gateway parametrized over the KV connector so BOTH handoff paths
    stay covered through the router — 'auto' must resolve to 'device' since
    both legs are in-proc."""
    from smg_tpu.gateway.router import RouterConfig

    loop = asyncio.new_event_loop()
    ctx = AppContext(
        policy="round_robin",
        router_config=RouterConfig(kv_connector=request.param),
    )
    ctx.tokenizers.register("tiny-test", MockTokenizer(), default=True)
    p_engine = make_engine()
    d_engine = make_engine()

    async def _setup():
        ctx.registry.add(Worker(
            worker_id="prefill-0", client=InProcWorkerClient(p_engine),
            model_id="tiny-test", worker_type=WorkerType.PREFILL,
        ))
        ctx.registry.add(Worker(
            worker_id="decode-0", client=InProcWorkerClient(d_engine),
            model_id="tiny-test", worker_type=WorkerType.DECODE,
        ))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=120)

    tc = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.client = run, tc
    h.p_engine, h.d_engine = p_engine, d_engine
    # what the router must hand the prefill leg after auto-resolution
    h.kv_connector = "device" if request.param == "auto" else request.param
    yield h
    run(tc.close())
    loop.call_soon_threadsafe(loop.stop)
    p_engine.stop(); d_engine.stop()


def test_pd_chat_through_gateway(pd_gateway):
    async def go():
        resp = await pd_gateway.client.post(
            "/v1/chat/completions",
            json={"model": "tiny-test",
                  "messages": [{"role": "user", "content": "w5 w6 w7"}],
                  "max_tokens": 6, "temperature": 0, "ignore_eos": True},
        )
        return resp.status, await resp.json()

    status, body = pd_gateway.run(go())
    assert status == 200, body
    assert body["choices"][0]["message"]["content"].startswith("w")
    assert body["usage"]["completion_tokens"] == 6
    # prefill ran on the prefill engine, decode tokens on the decode engine
    assert pd_gateway.p_engine.scheduler.num_prefill_tokens > 0
    assert pd_gateway.d_engine.scheduler.num_prefill_tokens == 0
    assert pd_gateway.d_engine.scheduler.num_decode_tokens > 0


def test_pd_decode_decision_reconciles(pd_gateway):
    """The decode-leg RouteDecision is held across PD dispatch and reconciled
    against the first decode chunk's cached_tokens — adopt_prefilled imports
    the prompt KV without consulting the decode worker's prefix cache, so
    the honest actual is 0 (regression: _execute_pd used to drop the
    decision, leaving PD traffic out of the reconciliation accounting)."""
    async def go():
        resp = await pd_gateway.client.post(
            "/v1/chat/completions",
            json={"model": "tiny-test",
                  "messages": [{"role": "user", "content": "w11 w12 w13"}],
                  "max_tokens": 2, "temperature": 0, "ignore_eos": True},
        )
        assert resp.status == 200, await resp.text()
        dbg = await pd_gateway.client.get("/debug/router")
        assert dbg.status == 200
        return await dbg.json()

    body = pd_gateway.run(go())
    reconciled = [
        d for d in body["models"]["tiny-test"]["decisions"]
        if d["reconciled"] and d["chosen"] == "decode-0"
    ]
    assert reconciled, "PD decode decision never reconciled"
    assert reconciled[-1]["worker_cached_tokens"] == 0
    assert body["reconciliation"]["decode-0"]["count"] >= 1


def test_pd_streaming(pd_gateway):
    async def go():
        resp = await pd_gateway.client.post(
            "/v1/chat/completions",
            json={"model": "tiny-test",
                  "messages": [{"role": "user", "content": "w9 w10"}],
                  "max_tokens": 4, "temperature": 0, "ignore_eos": True,
                  "stream": True},
        )
        return await resp.text()

    raw = pd_gateway.run(go())
    frames = [l for l in raw.splitlines() if l.startswith("data: ")]
    assert frames[-1] == "data: [DONE]"
    assert len(frames) >= 4


def test_engine_level_device_connector(cpu_devices):
    """Device connector: KV hands over as on-device jax.Arrays between two
    engines pinned to DIFFERENT devices — jax.device_put moves the pages
    device-to-device (the ICI/DCN path on TPU) and decode output stays
    token-exact with a single-engine reference."""
    import jax

    a = make_engine(devices=[cpu_devices[0]])
    b = make_engine(devices=[cpu_devices[1]])
    prompt = list(range(5, 45))
    sp = SamplingParams(temperature=0.0, max_new_tokens=8, ignore_eos=True)
    ref = a.generate(prompt_ids=prompt, sampling=sp)
    a.flush_cache()

    # engines really live on different devices
    assert a.runner.k_cache.devices() == {cpu_devices[0]}
    assert b.runner.k_cache.devices() == {cpu_devices[1]}

    export = a.prefill_export(prompt, sp, connector="device")
    assert export["connector"] == "device"
    assert isinstance(export["k"], jax.Array), type(export["k"])
    assert isinstance(export["v"], jax.Array)
    # payload exported on A's device; import lands it on B's
    assert export["k"].devices() == {cpu_devices[0]}

    outs, done = [], threading.Event()

    def cb(o):
        outs.append(o)
        if o.finished:
            done.set()

    b.submit_prefilled(prompt, export["first_token"], export["k"], export["v"], sp,
                       on_output=cb)
    budget = 300
    while not done.is_set() and budget:
        b.step()
        budget -= 1
    tokens = [t for o in outs for t in o.new_token_ids]
    assert tokens == ref.token_ids, (tokens, ref.token_ids)
    assert b.scheduler.num_prefill_tokens == 0


def test_gateway_routes_configured_connector(pd_gateway):
    """The router hands the configured connector to the prefill leg (and
    'auto' with in-proc legs on both sides resolves to 'device' — covered by
    the fixture's device parametrization)."""
    calls = []
    orig = pd_gateway.p_engine.prefill_export

    def spy(prompt_ids, sampling, connector="host"):
        calls.append(connector)
        return orig(prompt_ids, sampling, connector=connector)

    pd_gateway.p_engine.prefill_export = spy
    try:
        async def go():
            resp = await pd_gateway.client.post(
                "/v1/chat/completions",
                json={"model": "tiny-test",
                      "messages": [{"role": "user", "content": "w21 w22"}],
                      "max_tokens": 3, "temperature": 0, "ignore_eos": True},
            )
            return resp.status
        assert pd_gateway.run(go()) == 200
    finally:
        pd_gateway.p_engine.prefill_export = orig
    assert calls == [pd_gateway.kv_connector], calls


def test_engine_level_transfer_connector():
    """Cross-host transfer connector (jax.experimental.transfer): the
    prefill leg offers device KV under a uuid, the decode leg pulls it
    device-to-device; only the descriptor crosses the control path.
    Token-exact vs local generation (VERDICT r3 next-round #4)."""
    from smg_tpu.engine.kv_transfer import transfer_available

    if not transfer_available():
        pytest.skip("jax.experimental.transfer unavailable")
    a = make_engine()
    b = make_engine()
    try:
        prompt = list(range(7, 47))
        sp = SamplingParams(temperature=0.0, max_new_tokens=8, ignore_eos=True)
        ref = a.generate(prompt_ids=prompt, sampling=sp)
        a.flush_cache()

        export = a.prefill_export(prompt, sp, connector="transfer")
        assert export["connector"] == "transfer"
        desc = export["k"]
        assert "transfer_address" in desc and desc["transfer_uuid"]
        assert tuple(desc["kv_shape"])[0] == 4  # L layers

        outs, done = [], threading.Event()

        def cb(o):
            outs.append(o)
            if o.finished:
                done.set()

        b.submit_prefilled(prompt, export["first_token"], export["k"],
                           export["v"], sp, on_output=cb)
        for _ in range(300):
            b.step()
            if done.is_set():
                break
        tokens = [t for o in outs for t in o.new_token_ids]
        assert tokens == ref.token_ids
        assert b.scheduler.num_prefill_tokens == 0
    finally:
        a.stop(); b.stop()


def test_transfer_pd_over_real_grpc():
    """Full PD pair over gRPC with the transfer connector: gRPC carries
    only the pull descriptor; tokens match a plain generation."""
    from smg_tpu.engine.kv_transfer import transfer_available

    if not transfer_available():
        pytest.skip("jax.experimental.transfer unavailable")
    from smg_tpu.gateway.worker_client import WorkerGenerateRequest
    from smg_tpu.rpc.client import GrpcWorkerClient
    from smg_tpu.rpc.server import serve_worker_async

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=300):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    p_eng, d_eng = make_engine("pd-p"), make_engine("pd-d")
    p_eng.start(); d_eng.start()
    try:
        async def _setup():
            ps = await serve_worker_async(p_eng, port=0, host="127.0.0.1")
            ds = await serve_worker_async(d_eng, port=0, host="127.0.0.1")
            return (ps, GrpcWorkerClient(f"127.0.0.1:{ps._bound_port}"),
                    ds, GrpcWorkerClient(f"127.0.0.1:{ds._bound_port}"))

        ps, pc, ds, dc = run(_setup())
        prompt = list(range(9, 49))
        sp = SamplingParams(temperature=0.0, max_new_tokens=6, ignore_eos=True)
        ref = p_eng.generate(prompt_ids=prompt, sampling=sp)
        p_eng.flush_cache()

        async def go():
            info = await pc.get_model_info()
            assert info["supports_kv_transfer"] is True
            export = await pc.prefill_export(prompt, sp, connector="transfer")
            assert export["connector"] == "transfer"
            req = WorkerGenerateRequest(rid="pd-x", input_ids=prompt, sampling=sp)
            toks = []
            async for chunk in dc.generate_prefilled(
                req, export["first_token"], export["k"], export["v"]
            ):
                toks.extend(chunk.token_ids)
            return toks

        tokens = run(go())
        assert tokens == ref.token_ids
        assert d_eng.scheduler.num_prefill_tokens == 0

        async def _teardown():
            await pc.close(); await dc.close()
            await ps.stop(grace=None); await ds.stop(grace=None)

        run(_teardown())
    finally:
        loop.call_soon_threadsafe(loop.stop)
        p_eng.stop(); d_eng.stop()


def test_transfer_offer_lifecycle():
    """Offers are tracked; consumed offers stop tracking, abandoned offers
    are reclaimed by self-pull (releasing the pinned arrays)."""
    import time

    from smg_tpu.engine.kv_transfer import TransferManager, transfer_available

    if not transfer_available():
        pytest.skip("jax.experimental.transfer unavailable")
    import jax
    import jax.numpy as jnp

    mgr = TransferManager(jax.devices("cpu")[0])
    u1 = mgr.offer([jnp.zeros((2, 2))])
    u2 = mgr.offer([jnp.ones((3,))])
    assert set(mgr._pending) == {u1, u2}
    # success path
    assert mgr.mark_consumed(u1)
    assert not mgr.mark_consumed(u1)
    assert set(mgr._pending) == {u2}
    # failure path: reclaim self-pulls in a daemon thread
    assert mgr.reclaim(u2)
    assert not mgr._pending
    for _ in range(100):  # wait for the drain thread to consume the offer
        if not any(t.name.startswith("kv-reclaim") and t.is_alive()
                   for t in __import__("threading").enumerate()):
            break
        time.sleep(0.05)
    assert not any(t.name.startswith("kv-reclaim") and t.is_alive()
                   for t in __import__("threading").enumerate())


# ---- PD over HTTP workers (r5: pd_router.rs parity) ----


def _make_pd_http_worker(seen: list, role: str, model_id: str = "pd-http-model"):
    """OpenAI-wire engine worker that records the bootstrap metadata the
    gateway injected (the real engines use it to rendezvous KV transfer)."""
    import json as _json

    from aiohttp import web

    async def models(request):
        return web.json_response({"object": "list", "data": [{"id": model_id}]})

    async def health(request):
        return web.Response(text="ok")

    async def chat(request):
        body = await request.json()
        seen.append({"role": role, "path": "/v1/chat/completions", "body": body})
        if body.get("stream"):
            resp = web.StreamResponse(headers={"content-type": "text/event-stream"})
            await resp.prepare(request)
            for frag in (f"{role} ", "stream"):
                f = {"id": "c1", "object": "chat.completion.chunk",
                     "choices": [{"index": 0, "delta": {"content": frag}}]}
                await resp.write(f"data: {_json.dumps(f)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        return web.json_response({
            "id": "c1", "object": "chat.completion", "created": 1,
            "model": body.get("model"),
            "choices": [{"index": 0,
                         "message": {"role": "assistant", "content": f"{role} answer"},
                         "finish_reason": "stop"}],
            "usage": {"prompt_tokens": 2, "completion_tokens": 2, "total_tokens": 4},
        })

    async def generate(request):
        body = await request.json()
        seen.append({"role": role, "path": "/generate", "body": body})
        return web.json_response({
            "text": f"{role} generated", "output_ids": [1, 2],
            "meta_info": {"id": body.get("rid") or "g1",
                          "finish_reason": {"type": "stop"}},
        })

    from aiohttp import web as _web

    app = _web.Application()
    app.router.add_get("/v1/models", models)
    app.router.add_get("/health", health)
    app.router.add_post("/v1/chat/completions", chat)
    app.router.add_post("/generate", generate)
    return app


@pytest.fixture(scope="module")
def pd_http_gateway():
    import threading

    from aiohttp.test_utils import TestClient, TestServer

    from smg_tpu.gateway.server import AppContext, build_app

    loop = asyncio.new_event_loop()
    seen: list = []
    ctx = AppContext(policy="round_robin")

    async def _setup():
        servers = []
        for role in ("prefill", "decode"):
            s = TestServer(_make_pd_http_worker(seen, role))
            await s.start_server()
            servers.append((role, s))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        for role, s in servers:
            url = str(s.make_url("")).rstrip("/")
            r = await tc.post("/workers", json={
                "url": url, "worker_type": role,
                "bootstrap_host": "10.0.0.7" if role == "prefill" else None,
                "bootstrap_port": 8998 if role == "prefill" else None,
            })
            assert r.status == 200, await r.text()
        return tc, [s for _, s in servers]

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=60)

    tc, servers = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.client, h.seen = run, tc, seen
    yield h
    run(tc.close())
    for s in servers:
        run(s.close())
    loop.call_soon_threadsafe(loop.stop)


def test_pd_http_chat_dual_dispatch(pd_http_gateway):
    """Chat over HTTP PD: both legs receive the request with IDENTICAL
    bootstrap metadata (prefill worker's host/port + shared random room);
    the client sees the decode leg's answer."""
    h = pd_http_gateway
    h.seen.clear()

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "pd-http-model",
            "messages": [{"role": "user", "content": "hi"}],
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    assert body["choices"][0]["message"]["content"] == "decode answer"
    roles = sorted(s["role"] for s in h.seen)
    assert roles == ["decode", "prefill"]
    p = next(s["body"] for s in h.seen if s["role"] == "prefill")
    d = next(s["body"] for s in h.seen if s["role"] == "decode")
    assert p["bootstrap_host"] == d["bootstrap_host"] == "10.0.0.7"
    assert p["bootstrap_port"] == d["bootstrap_port"] == 8998
    assert p["bootstrap_room"] == d["bootstrap_room"]
    assert isinstance(p["bootstrap_room"], int)
    # the prefill leg is forced non-streaming
    assert p["stream"] is False


def test_pd_http_chat_streaming_from_decode(pd_http_gateway):
    h = pd_http_gateway
    h.seen.clear()

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "pd-http-model", "stream": True,
            "messages": [{"role": "user", "content": "hi"}],
        })
        return await r.text()

    raw = h.run(go())
    text = "".join(
        (json.loads(l[6:])["choices"][0]["delta"].get("content") or "")
        for l in raw.splitlines()
        if l.startswith("data: ") and l != "data: [DONE]"
        and json.loads(l[6:]).get("choices")
    )
    assert text == "decode stream"
    p = next(s["body"] for s in h.seen if s["role"] == "prefill")
    assert p["stream"] is False  # prefill leg never streams


def test_pd_http_generate_passthrough(pd_http_gateway):
    """/generate passthrough parity: raw body forwarded to both legs with
    bootstrap metadata, decode's native response returned."""
    h = pd_http_gateway
    h.seen.clear()

    async def go():
        r = await h.client.post("/generate", json={
            "text": "complete this", "sampling_params": {"max_new_tokens": 4},
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    assert body["text"] == "decode generated"
    gen = [s for s in h.seen if s["path"] == "/generate"]
    assert sorted(s["role"] for s in gen) == ["decode", "prefill"]
    p = next(s["body"] for s in gen if s["role"] == "prefill")
    d = next(s["body"] for s in gen if s["role"] == "decode")
    assert p["bootstrap_room"] == d["bootstrap_room"]
    assert p["text"] == "complete this"  # raw body passthrough
