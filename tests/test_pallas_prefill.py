"""Parity tests: pallas paged prefill attention (interpret mode) vs the XLA
gather path — the two implementations the runner switches between (VERDICT
r2 #3; SURVEY.md §7 hard part (b))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smg_tpu.ops.attention import attention_prefill, gather_seq_kv
from smg_tpu.ops.pallas.prefill_attention import paged_attention_prefill


def _setup(T, H, D, K, ps, mp, prefix_len, t_real, P=64, seed=0):
    """Build a cache holding a real prefix + the scattered chunk, exactly as
    forward_prefill does, and return everything both paths need."""
    rng = np.random.default_rng(seed)
    L = 3
    layer = 1
    KD = K * D
    k_cache = jnp.asarray(rng.standard_normal((L, P, ps, KD)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((L, P, ps, KD)), jnp.float32)
    # one sequence owning mp distinct pages (skip garbage page 0)
    page_table = jnp.asarray(rng.permutation(P - 1)[:mp] + 1, jnp.int32)

    q = jnp.asarray(rng.standard_normal((T, H, D)), jnp.float32)
    ck = jnp.asarray(rng.standard_normal((T, KD)), jnp.float32)
    cv = jnp.asarray(rng.standard_normal((T, KD)), jnp.float32)

    # scatter the chunk into the cache (prefill does this before attention,
    # so the XLA gather sees chunk tokens through the page table)
    pos = prefix_len + np.arange(T)
    valid = (np.arange(T) < t_real) & (pos < mp * ps)
    pos_c = np.minimum(pos, mp * ps - 1)
    pt_np = np.asarray(page_table)
    dest = np.where(valid, pt_np[pos_c // ps] * ps + pos_c % ps, 0)
    kf = k_cache.reshape(L, P * ps, KD)
    vf = v_cache.reshape(L, P * ps, KD)
    kf = kf.at[layer, dest].set(ck)
    vf = vf.at[layer, dest].set(cv)
    k_cache = kf.reshape(L, P, ps, KD)
    v_cache = vf.reshape(L, P, ps, KD)
    return q, ck, cv, k_cache, v_cache, layer, page_table


def _xla_reference(q, k_cache, v_cache, layer, page_table, prefix_len, t_real, K,
                   softcap=None, window=None):
    T, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    k_ctx, v_ctx = gather_seq_kv(k_cache[layer], v_cache[layer], page_table, K)
    pos = prefix_len + jnp.arange(T)
    return attention_prefill(q, k_ctx, v_ctx, pos, jnp.int32(prefix_len + t_real),
                             scale, softcap=softcap, window=window)


@pytest.mark.parametrize(
    "T,H,D,K,prefix_len,t_real",
    [
        (16, 8, 64, 8, 160, 16),   # llama-1B shape: MHA-ish, C=2 lane fold
        (16, 8, 64, 2, 160, 16),   # GQA 4:1 with C=2
        (32, 4, 128, 2, 96, 32),   # D=128: C=1 plain slice
        (16, 8, 64, 8, 0, 16),     # cold chunk: no prefix pages at all
        (16, 8, 64, 8, 137, 11),   # ragged: prefix not page-aligned, padded rows
    ],
)
def test_parity_vs_xla(T, H, D, K, prefix_len, t_real):
    ps, mp = 16, 24
    q, ck, cv, k_cache, v_cache, layer, page_table = _setup(
        T, H, D, K, ps, mp, prefix_len, t_real
    )
    scale = 1.0 / np.sqrt(D)
    got = paged_attention_prefill(
        q, ck, cv, k_cache, v_cache, layer, page_table,
        prefix_len, t_real, scale, interpret=True,
    )
    want = _xla_reference(q, k_cache, v_cache, layer, page_table,
                          prefix_len, t_real, K)
    # rows beyond t_real are garbage in both paths; compare valid rows only
    np.testing.assert_allclose(
        np.asarray(got[:t_real]), np.asarray(want[:t_real]), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize(
    "softcap,window",
    [
        (30.0, None),   # Gemma-2 softcap only
        (None, 100),    # window cuts into the prefix (prefix 160)
        (None, 8),      # window smaller than the chunk: cuts intra-chunk too
        (30.0, 100),    # both together (Gemma-2 local layers)
        (None, 4096),   # window wider than everything = global
        (None, 0),      # window<=0 means global
    ],
)
def test_parity_softcap_window(softcap, window):
    """Sliding-window + logit-softcap masks in the pallas prefill kernel
    match the XLA path (VERDICT r4 next-round #1)."""
    T, H, D, K, prefix_len, t_real = 16, 8, 64, 8, 160, 16
    ps, mp = 16, 24
    q, ck, cv, k_cache, v_cache, layer, page_table = _setup(
        T, H, D, K, ps, mp, prefix_len, t_real
    )
    scale = 1.0 / np.sqrt(D)
    w = None if window is None else jnp.int32(window)
    got = paged_attention_prefill(
        q, ck, cv, k_cache, v_cache, layer, page_table,
        prefix_len, t_real, scale, softcap=softcap, window=w, interpret=True,
    )
    want = _xla_reference(q, k_cache, v_cache, layer, page_table,
                          prefix_len, t_real, K, softcap=softcap, window=w)
    np.testing.assert_allclose(
        np.asarray(got[:t_real]), np.asarray(want[:t_real]), rtol=2e-5, atol=2e-5
    )


def test_window_skips_out_of_window_prefix_blocks():
    """Prefix blocks wholly below every query's window must never be read:
    poison them with NaN and require a finite, XLA-matching result."""
    T, H, D, K, ps = 16, 8, 64, 8, 16
    mp, P = 40, 96
    prefix_len, t_real = 37 * 16 + 5, 16  # 597 tokens
    window = 64  # earliest query at 597: window floor 534 → blocks 0-3 dead
    q, ck, cv, k_cache, v_cache, layer, page_table = _setup(
        T, H, D, K, ps, mp, prefix_len, t_real, P=P
    )
    want = _xla_reference(q, k_cache, v_cache, layer, page_table,
                          prefix_len, t_real, K, window=jnp.int32(window))
    # poison pages holding positions < 512 (first 4 of 5 128-token blocks)
    pt = np.asarray(page_table)
    kc, vc = np.array(k_cache), np.array(v_cache)
    for i in range(32):
        kc[layer, pt[i]] = np.nan
        vc[layer, pt[i]] = np.nan
    scale = 1.0 / np.sqrt(D)
    got = paged_attention_prefill(
        q, ck, cv, jnp.asarray(kc), jnp.asarray(vc), layer, page_table,
        prefix_len, t_real, scale, window=jnp.int32(window), interpret=True,
    )
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_long_prefix_multiblock():
    """Prefix spanning several 128-token DMA blocks exercises the streaming
    loop + online softmax merge across blocks."""
    T, H, D, K, ps = 16, 8, 64, 8, 16
    mp, P = 40, 96
    prefix_len, t_real = 37 * 16 + 5, 16  # 597 tokens: 5 blocks, ragged tail
    q, ck, cv, k_cache, v_cache, layer, page_table = _setup(
        T, H, D, K, ps, mp, prefix_len, t_real, P=P
    )
    scale = 1.0 / np.sqrt(D)
    got = paged_attention_prefill(
        q, ck, cv, k_cache, v_cache, layer, page_table,
        prefix_len, t_real, scale, interpret=True,
    )
    want = _xla_reference(q, k_cache, v_cache, layer, page_table,
                          prefix_len, t_real, K)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_prefill_pallas_impl_matches_xla(tiny_cfg):
    """End-to-end through forward_prefill: attn_impl='pallas' (interpret)
    token-exact vs the default XLA path."""
    from smg_tpu.models.registry import get_model
    from smg_tpu.ops.rope import rope_frequencies

    cfg = tiny_cfg
    module = get_model(cfg.arch)
    params = module.init_params(cfg, jax.random.PRNGKey(0))
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                            cfg.rope_scaling))
    P, ps, mp = 32, 16, 8
    KD = cfg.num_kv_heads * cfg.head_dim
    kc = jnp.zeros((cfg.num_layers, P, ps, KD), jnp.float32)
    vc = jnp.zeros_like(kc)
    page_table = jnp.arange(1, mp + 1, dtype=jnp.int32)
    tokens = jnp.arange(5, 5 + 32, dtype=jnp.int32) % cfg.vocab_size

    lo_x, kcx, vcx = module.forward_prefill(
        params, cfg, inv_freq, tokens, jnp.int32(0), jnp.int32(32),
        kc, vc, page_table,
    )
    lo_p, kcp, vcp = module.forward_prefill(
        params, cfg, inv_freq, tokens, jnp.int32(0), jnp.int32(32),
        kc, vc, page_table, attn_impl="pallas_interpret",
    )
    np.testing.assert_allclose(np.asarray(lo_x), np.asarray(lo_p),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(kcx), np.asarray(kcp), atol=1e-6)
    np.testing.assert_allclose(np.asarray(vcx), np.asarray(vcp), atol=1e-6)
