"""Provider breadth r5 (VERDICT r4 next-round #5): xAI adapter with the
Responses-input rewrite, AWS Bedrock Converse adapter with SigV4 signing,
and the openai_bridge (Anthropic /v1/messages front over OpenAI-format
provider backends) — all against protocol-accurate local mock upstreams."""

import asyncio
import datetime
import json
import threading

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.gateway.providers import ProviderSpec
from smg_tpu.gateway.providers.bedrock import (
    chat_to_converse,
    converse_to_chat,
    sigv4_headers,
)
from smg_tpu.gateway.providers.xai import transform_responses_input
from smg_tpu.gateway.server import AppContext, build_app

# ---------------- unit: xai input rewrite ----------------


def test_xai_responses_input_rewrite():
    body = {
        "model": "grok-4",
        "input": [
            {"type": "message", "role": "user", "id": "itm_1", "status": "done",
             "content": [{"type": "input_text", "text": "hi"}]},
            {"type": "message", "role": "assistant",
             "content": [{"type": "output_text", "text": "prior answer"}]},
        ],
    }
    out = transform_responses_input(body)
    assert "id" not in out["input"][0] and "status" not in out["input"][0]
    assert out["input"][0]["content"][0]["type"] == "input_text"  # untouched
    assert out["input"][1]["content"][0]["type"] == "input_text"  # rewritten
    assert out["input"][1]["content"][0]["text"] == "prior answer"


def test_xai_rewrite_ignores_string_input():
    assert transform_responses_input({"input": "plain"})["input"] == "plain"


# ---------------- unit: bedrock translation ----------------


def test_chat_to_converse_shapes():
    from smg_tpu.protocols.openai import (
        ChatCompletionRequest,
        ChatMessage,
        FunctionDef,
        Tool,
    )

    req = ChatCompletionRequest(
        model="bedrock/claude", max_tokens=64, temperature=0.3, top_p=0.9,
        stop=["END"],
        messages=[
            ChatMessage(role="system", content="be brief"),
            ChatMessage(role="user", content="weather?"),
            ChatMessage(role="assistant", content=None, tool_calls=[{
                "id": "t1", "type": "function",
                "function": {"name": "get_weather", "arguments": '{"c": "P"}'},
            }]),
            ChatMessage(role="tool", content="18C", tool_call_id="t1"),
        ],
        tools=[Tool(function=FunctionDef(name="get_weather", description="w",
                                         parameters={"type": "object"}))],
    )
    body = chat_to_converse(req)
    assert body["system"] == [{"text": "be brief"}]
    assert body["messages"][0] == {"role": "user", "content": [{"text": "weather?"}]}
    tu = body["messages"][1]["content"][0]["toolUse"]
    assert tu["name"] == "get_weather" and tu["input"] == {"c": "P"}
    tr = body["messages"][2]["content"][0]["toolResult"]
    assert tr["toolUseId"] == "t1"
    assert body["inferenceConfig"] == {
        "maxTokens": 64, "temperature": 0.3, "topP": 0.9, "stopSequences": ["END"],
    }
    spec = body["toolConfig"]["tools"][0]["toolSpec"]
    assert spec["name"] == "get_weather"
    assert spec["inputSchema"] == {"json": {"type": "object"}}


def test_converse_to_chat_tool_use():
    data = {
        "output": {"message": {"role": "assistant", "content": [
            {"text": "checking"},
            {"toolUse": {"toolUseId": "tu1", "name": "f", "input": {"a": 1}}},
        ]}},
        "stopReason": "tool_use",
        "usage": {"inputTokens": 5, "outputTokens": 9, "totalTokens": 14},
    }
    out = converse_to_chat(data, "bedrock/claude")
    msg = out["choices"][0]["message"]
    assert msg["content"] == "checking"
    assert msg["tool_calls"][0]["function"]["name"] == "f"
    assert json.loads(msg["tool_calls"][0]["function"]["arguments"]) == {"a": 1}
    assert out["choices"][0]["finish_reason"] == "tool_calls"
    assert out["usage"]["total_tokens"] == 14


def test_sigv4_deterministic_and_secret_sensitive():
    now = datetime.datetime(2026, 7, 30, 12, 0, 0, tzinfo=datetime.timezone.utc)
    h1 = sigv4_headers("POST", "https://bedrock-runtime.us-west-2.amazonaws.com/model/m/converse",
                       b"{}", "AKID", "SECRET", "us-west-2", now=now)
    h2 = sigv4_headers("POST", "https://bedrock-runtime.us-west-2.amazonaws.com/model/m/converse",
                       b"{}", "AKID", "SECRET", "us-west-2", now=now)
    h3 = sigv4_headers("POST", "https://bedrock-runtime.us-west-2.amazonaws.com/model/m/converse",
                       b"{}", "AKID", "OTHER", "us-west-2", now=now)
    assert h1 == h2
    assert h1["authorization"] != h3["authorization"]
    assert h1["x-amz-date"] == "20260730T120000Z"
    assert h1["authorization"].startswith(
        "AWS4-HMAC-SHA256 Credential=AKID/20260730/us-west-2/bedrock/aws4_request, "
        "SignedHeaders=host;x-amz-date, Signature="
    )


# ---------------- mock upstreams ----------------


def make_mock_xai(seen: list):
    async def chat(request: web.Request):
        body = await request.json()
        seen.append({"path": "/chat/completions", "body": body})
        return web.json_response({
            "id": "x1", "object": "chat.completion", "model": body["model"],
            "choices": [{"index": 0, "message": {"role": "assistant",
                                                 "content": "grok says hi"},
                         "finish_reason": "stop"}],
            "usage": {"prompt_tokens": 3, "completion_tokens": 2, "total_tokens": 5},
        })

    async def responses(request: web.Request):
        body = await request.json()
        seen.append({"path": "/responses", "body": body})
        return web.json_response({
            "id": "resp_x1", "object": "response", "status": "completed",
            "model": body["model"],
            "output": [{"type": "message", "role": "assistant",
                        "content": [{"type": "output_text", "text": "ok"}]}],
        })

    app = web.Application()
    app.router.add_post("/chat/completions", chat)
    app.router.add_post("/responses", responses)
    return app


def make_mock_bedrock(seen: list):
    async def converse(request: web.Request):
        body = await request.json()
        seen.append({
            "path": str(request.path),
            "headers": {k.lower(): v for k, v in request.headers.items()},
            "body": body,
        })
        if body.get("toolConfig"):
            content = [{"toolUse": {"toolUseId": "tu1", "name": "get_weather",
                                    "input": {"city": "Paris"}}}]
            stop = "tool_use"
        else:
            content = [{"text": "bedrock says hi"}]
            stop = "end_turn"
        return web.json_response({
            "output": {"message": {"role": "assistant", "content": content}},
            "stopReason": stop,
            "usage": {"inputTokens": 4, "outputTokens": 6, "totalTokens": 10},
        })

    async def converse_stream(request: web.Request):
        body = await request.json()
        seen.append({"path": str(request.path), "body": body})
        resp = web.StreamResponse(headers={"content-type": "text/event-stream"})
        await resp.prepare(request)
        frames = [
            {"messageStart": {"role": "assistant"}},
            {"contentBlockDelta": {"delta": {"text": "hi "}, "contentBlockIndex": 0}},
            {"contentBlockDelta": {"delta": {"text": "from bedrock"}, "contentBlockIndex": 0}},
            {"contentBlockStart": {"start": {"toolUse": {
                "toolUseId": "tu9", "name": "get_weather"}}, "contentBlockIndex": 1}},
            {"contentBlockDelta": {"delta": {"toolUse": {"input": '{"city":'}},
             "contentBlockIndex": 1}},
            {"contentBlockDelta": {"delta": {"toolUse": {"input": ' "Paris"}'}},
             "contentBlockIndex": 1}},
            {"messageStop": {"stopReason": "tool_use"}},
            {"metadata": {"usage": {"inputTokens": 4, "outputTokens": 6,
                                    "totalTokens": 10}}},
        ]
        for f in frames:
            await resp.write(f"data: {json.dumps(f)}\n\n".encode())
        await resp.write_eof()
        return resp

    app = web.Application()
    app.router.add_post("/model/{model}/converse", converse)
    app.router.add_post("/model/{model}/converse-stream", converse_stream)
    return app


def make_mock_openai_for_bridge(seen: list):
    async def chat(request: web.Request):
        body = await request.json()
        seen.append({"body": body})
        if body.get("stream"):
            resp = web.StreamResponse(headers={"content-type": "text/event-stream"})
            await resp.prepare(request)
            frames = [
                {"id": "u1", "object": "chat.completion.chunk", "model": body["model"],
                 "choices": [{"index": 0, "delta": {"role": "assistant"}}]},
                {"id": "u1", "object": "chat.completion.chunk", "model": body["model"],
                 "choices": [{"index": 0, "delta": {"content": "bridged "}}]},
                {"id": "u1", "object": "chat.completion.chunk", "model": body["model"],
                 "choices": [{"index": 0, "delta": {"content": "text"}}]},
                # protocol-accurate fragmented tool-call streaming: opening
                # delta carries id+name, arguments arrive as bare fragments
                {"id": "u1", "object": "chat.completion.chunk", "model": body["model"],
                 "choices": [{"index": 0, "delta": {
                     "tool_calls": [{"index": 0, "id": "call_7", "type": "function",
                                     "function": {"name": "f", "arguments": ""}}]
                 }}]},
                {"id": "u1", "object": "chat.completion.chunk", "model": body["model"],
                 "choices": [{"index": 0, "delta": {
                     "tool_calls": [{"index": 0,
                                     "function": {"arguments": '{"x":'}}]
                 }}]},
                {"id": "u1", "object": "chat.completion.chunk", "model": body["model"],
                 "choices": [{"index": 0, "delta": {
                     "tool_calls": [{"index": 0,
                                     "function": {"arguments": " 1}"}}]
                 }}]},
                {"id": "u1", "object": "chat.completion.chunk", "model": body["model"],
                 "choices": [{"index": 0, "delta": {}, "finish_reason": "tool_calls"}]},
                {"id": "u1", "object": "chat.completion.chunk", "model": body["model"],
                 "choices": [],
                 "usage": {"prompt_tokens": 11, "completion_tokens": 7,
                           "total_tokens": 18}},
            ]
            for f in frames:
                await resp.write(f"data: {json.dumps(f)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        return web.json_response({
            "id": "u1", "object": "chat.completion", "model": body["model"],
            "choices": [{"index": 0, "message": {
                "role": "assistant", "content": "bridged answer",
                "tool_calls": [{"id": "call_9", "type": "function",
                                "function": {"name": "f",
                                             "arguments": '{"x": 2}'}}],
            }, "finish_reason": "tool_calls"}],
            "usage": {"prompt_tokens": 5, "completion_tokens": 4, "total_tokens": 9},
        })

    app = web.Application()
    app.router.add_post("/chat/completions", chat)
    return app


# ---------------- fixture ----------------


@pytest.fixture(scope="module")
def v2_gateway():
    loop = asyncio.new_event_loop()
    seen = {"xai": [], "bedrock": [], "bridge": []}
    ctx = AppContext(policy="round_robin")

    async def _setup():
        mocks = {}
        for kind, maker in (("xai", make_mock_xai),
                            ("bedrock", make_mock_bedrock),
                            ("bridge", make_mock_openai_for_bridge)):
            server = TestServer(maker(seen[kind]))
            await server.start_server()
            mocks[kind] = server
        ctx.providers.register(ProviderSpec(
            name="xai", kind="xai",
            base_url=str(mocks["xai"].make_url("")).rstrip("/"),
            api_key="xai-test", models=["grok-4"],
        ))
        ctx.providers.register(ProviderSpec(
            name="bedrock", kind="bedrock",
            base_url=str(mocks["bedrock"].make_url("")).rstrip("/"),
            api_key="AKID:SECRET",
            models=["anthropic.claude-3-sonnet"],
        ))
        ctx.providers.register(ProviderSpec(
            name="oai-bridge", kind="openai",
            base_url=str(mocks["bridge"].make_url("")).rstrip("/"),
            api_key="sk-b", models=["bridge-model"],
        ))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc, mocks

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=60)

    tc, mocks = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.client, h.seen = run, tc, seen
    yield h
    run(tc.close())
    for s in mocks.values():
        run(s.close())
    loop.call_soon_threadsafe(loop.stop)


# ---------------- xai ----------------


def test_xai_chat_roundtrip(v2_gateway):
    h = v2_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "grok-4", "messages": [{"role": "user", "content": "hi"}],
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    assert body["choices"][0]["message"]["content"] == "grok says hi"


def test_xai_responses_upstream_rewrite(v2_gateway):
    """The gateway rewrites replayed output_text items before xAI sees them."""
    h = v2_gateway

    async def go():
        r = await h.client.post("/v1/responses", json={
            "model": "grok-4",
            "input": [
                {"type": "message", "role": "user", "id": "a", "status": "done",
                 "content": [{"type": "input_text", "text": "q"}]},
                {"type": "message", "role": "assistant",
                 "content": [{"type": "output_text", "text": "prev"}]},
            ],
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    assert body["status"] == "completed"
    up = next(s for s in h.seen["xai"] if s["path"] == "/responses")
    items = up["body"]["input"]
    assert "id" not in items[0]
    assert items[1]["content"][0]["type"] == "input_text"


# ---------------- bedrock ----------------


def test_bedrock_chat_roundtrip_signed(v2_gateway):
    h = v2_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "anthropic.claude-3-sonnet",
            "messages": [{"role": "system", "content": "brief"},
                         {"role": "user", "content": "hello"}],
            "max_tokens": 32,
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    assert body["choices"][0]["message"]["content"] == "bedrock says hi"
    assert body["usage"]["total_tokens"] == 10
    up = h.seen["bedrock"][-1]
    assert up["path"].endswith("/converse")
    assert up["body"]["system"] == [{"text": "brief"}]
    assert up["body"]["messages"] == [
        {"role": "user", "content": [{"text": "hello"}]}
    ]
    auth = up["headers"]["authorization"]
    assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKID/")
    assert "SignedHeaders=host;x-amz-date" in auth
    assert "x-amz-date" in up["headers"]


def test_bedrock_tool_calls(v2_gateway):
    h = v2_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "anthropic.claude-3-sonnet",
            "messages": [{"role": "user", "content": "weather?"}],
            "tools": [{"type": "function", "function": {
                "name": "get_weather", "parameters": {"type": "object"}}}],
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    tc = body["choices"][0]["message"]["tool_calls"][0]
    assert tc["function"]["name"] == "get_weather"
    assert json.loads(tc["function"]["arguments"]) == {"city": "Paris"}
    assert body["choices"][0]["finish_reason"] == "tool_calls"


def test_bedrock_streaming(v2_gateway):
    h = v2_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "anthropic.claude-3-sonnet", "stream": True,
            "messages": [{"role": "user", "content": "weather?"}],
        })
        return await r.text()

    raw = h.run(go())
    chunks = [json.loads(l[6:]) for l in raw.splitlines()
              if l.startswith("data: ") and l != "data: [DONE]"]
    text = "".join(c["choices"][0]["delta"].get("content") or ""
                   for c in chunks if c.get("choices"))
    assert text == "hi from bedrock"
    opens = [tc for c in chunks if c.get("choices")
             for tc in c["choices"][0]["delta"].get("tool_calls") or []
             if (tc.get("function") or {}).get("name")]
    assert opens and opens[0]["function"]["name"] == "get_weather"
    args = "".join(tc["function"].get("arguments") or ""
                   for c in chunks if c.get("choices")
                   for tc in c["choices"][0]["delta"].get("tool_calls") or [])
    assert json.loads(args) == {"city": "Paris"}
    finishes = [c["choices"][0].get("finish_reason")
                for c in chunks if c.get("choices")]
    assert "tool_calls" in finishes
    usage = [c["usage"] for c in chunks if c.get("usage")]
    assert usage and usage[-1]["total_tokens"] == 10


# ---------------- openai_bridge: anthropic front over openai provider ----------------


def test_bridge_messages_roundtrip(v2_gateway):
    h = v2_gateway

    async def go():
        r = await h.client.post("/v1/messages", json={
            "model": "bridge-model", "max_tokens": 64,
            "messages": [{"role": "user", "content": "do it"}],
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    types = [b["type"] for b in body["content"]]
    assert types == ["text", "tool_use"]
    assert body["content"][0]["text"] == "bridged answer"
    assert body["content"][1]["name"] == "f"
    assert body["content"][1]["input"] == {"x": 2}
    assert body["stop_reason"] == "tool_use"
    assert body["usage"]["input_tokens"] == 5
    # the upstream saw an OPENAI-format request
    up = h.seen["bridge"][-1]["body"]
    assert up["messages"] == [{"role": "user", "content": "do it"}]


def test_bridge_messages_streaming_grammar(v2_gateway):
    h = v2_gateway

    async def go():
        r = await h.client.post("/v1/messages", json={
            "model": "bridge-model", "max_tokens": 64, "stream": True,
            "messages": [{"role": "user", "content": "do it"}],
        })
        return await r.text()

    raw = h.run(go())
    events = []
    for block in raw.split("\n\n"):
        name = data = None
        for line in block.splitlines():
            if line.startswith("event: "):
                name = line[7:]
            elif line.startswith("data: "):
                data = json.loads(line[6:])
        if name:
            events.append((name, data))
    names = [n for n, _ in events]
    assert names[0] == "message_start"
    assert names[-2:] == ["message_delta", "message_stop"]
    text = "".join(d["delta"]["text"] for n, d in events
                   if n == "content_block_delta"
                   and d["delta"]["type"] == "text_delta")
    assert text == "bridged text"
    tools = [d for n, d in events if n == "content_block_start"
             and d["content_block"]["type"] == "tool_use"]
    assert len(tools) == 1, "fragmented args must NOT open extra blocks"
    assert tools[0]["content_block"]["name"] == "f"
    assert tools[0]["content_block"]["id"] == "call_7"
    tool_idx = tools[0]["index"]
    frags = [d["delta"]["partial_json"] for n, d in events
             if n == "content_block_delta"
             and d["delta"]["type"] == "input_json_delta"
             and d["index"] == tool_idx]
    assert json.loads("".join(frags)) == {"x": 1}
    # the tool_use block closes exactly once
    stops = [d for n, d in events if n == "content_block_stop"
             and d["index"] == tool_idx]
    assert len(stops) == 1
    md = next(d for n, d in events if n == "message_delta")
    assert md["delta"]["stop_reason"] == "tool_use"
    assert md["usage"] == {"input_tokens": 11, "output_tokens": 7}


def test_bridge_requests_usage_frame(v2_gateway):
    """The provider bridge must ask the upstream for the usage frame."""
    h = v2_gateway

    async def go():
        await h.client.post("/v1/messages", json={
            "model": "bridge-model", "max_tokens": 8, "stream": True,
            "messages": [{"role": "user", "content": "x"}],
        })
        return h.seen["bridge"][-1]["body"]

    body = h.run(go())
    assert (body.get("stream_options") or {}).get("include_usage") is True


def test_responses_via_chat_only_provider(v2_gateway):
    """A chat-only provider model still serves /v1/responses (synthesized
    over adapter.chat)."""
    h = v2_gateway

    async def go():
        r = await h.client.post("/v1/responses", json={
            "model": "bridge-model", "input": "do it",
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    kinds = [o["type"] for o in body["output"]]
    assert "message" in kinds and "function_call" in kinds
    msg = next(o for o in body["output"] if o["type"] == "message")
    assert msg["content"][0]["text"] == "bridged answer"
    fc = next(o for o in body["output"] if o["type"] == "function_call")
    assert fc["name"] == "f" and json.loads(fc["arguments"]) == {"x": 2}
    assert body["usage"]["total_tokens"] == 9


def test_bedrock_merges_consecutive_user_turns(v2_gateway):
    """Parallel tool results + the next user turn must merge into ONE
    Converse user message (Bedrock requires role alternation)."""
    h = v2_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "anthropic.claude-3-sonnet",
            "messages": [
                {"role": "user", "content": "weather in two cities"},
                {"role": "assistant", "content": None, "tool_calls": [
                    {"id": "t1", "type": "function",
                     "function": {"name": "w", "arguments": '{"c": "P"}'}},
                    {"id": "t2", "type": "function",
                     "function": {"name": "w", "arguments": '{"c": "L"}'}},
                ]},
                {"role": "tool", "content": "18C", "tool_call_id": "t1"},
                {"role": "tool", "content": "15C", "tool_call_id": "t2"},
                {"role": "user", "content": "so which is warmer?"},
            ],
        })
        return r.status, h.seen["bedrock"][-1]["body"]

    status, body = h.run(go())
    assert status == 200
    roles = [m["role"] for m in body["messages"]]
    assert roles == ["user", "assistant", "user"], roles
    merged = body["messages"][2]["content"]
    assert [list(b)[0] for b in merged] == ["toolResult", "toolResult", "text"]
