"""Auxiliary subsystems: multimodal preprocessing, checkpointing, k8s
discovery (against a fake API), weight loading."""

import asyncio
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest


# ---- multimodal ----

def test_smart_resize_rules():
    from smg_tpu.multimodal import smart_resize

    h, w = smart_resize(1000, 748, factor=28)
    assert h % 28 == 0 and w % 28 == 0
    # tiny image scales up to min_pixels
    h2, w2 = smart_resize(20, 20, factor=28, min_pixels=56 * 56)
    assert h2 * w2 >= 56 * 56
    # huge image scales down under max_pixels
    h3, w3 = smart_resize(10000, 10000, factor=28, max_pixels=1280 * 28 * 28)
    assert h3 * w3 <= 1280 * 28 * 28
    with pytest.raises(ValueError):
        smart_resize(10000, 10, factor=28)


def test_patchify_roundtrip_order():
    from smg_tpu.multimodal import patchify

    img = jnp.arange(8 * 8 * 3, dtype=jnp.float32).reshape(8, 8, 3)
    patches, grid = patchify(img, 4)
    assert grid == (2, 2)
    assert patches.shape == (4, 4 * 4 * 3)
    # first patch == top-left block, row-major
    np.testing.assert_array_equal(
        np.asarray(patches[0]).reshape(4, 4, 3), np.asarray(img[:4, :4]))
    np.testing.assert_array_equal(
        np.asarray(patches[1]).reshape(4, 4, 3), np.asarray(img[:4, 4:]))


def test_qwen2vl_processor():
    from smg_tpu.multimodal import get_image_processor

    proc = get_image_processor("Qwen2-VL-7B-Instruct")
    assert proc.name == "qwen2_vl"
    img = jnp.ones((300, 500, 3), jnp.uint8) * 128
    out = proc.process(img)
    gh, gw = out.grid
    assert gh % 2 == 0 and gw % 2 == 0  # mergeable
    assert out.num_placeholder_tokens == (gh // 2) * (gw // 2)
    assert out.pixel_values.shape == (gh * gw, 14 * 14 * 3)
    assert bool(jnp.isfinite(out.pixel_values).all())


def test_data_url_rejects_http():
    from smg_tpu.multimodal.image import decode_data_url

    with pytest.raises(ValueError):
        decode_data_url("http://example.com/x.png")


# ---- checkpoint ----

def test_checkpoint_roundtrip(tiny_cfg):
    from smg_tpu.engine.checkpoint import load_params, save_params
    from smg_tpu.models import llama

    params = llama.init_params(tiny_cfg, jax.random.PRNGKey(0))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ckpt")
        save_params(path, params)
        restored = load_params(path, like=params)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---- weight loading (HF safetensors) ----

def test_safetensors_weight_loading(tiny_cfg):
    from safetensors.numpy import save_file

    from smg_tpu.engine.config import EngineConfig
    from smg_tpu.models import llama
    from smg_tpu.models.weights import load_params as load_hf
    from smg_tpu.ops.rope import rope_frequencies

    cfg = tiny_cfg
    E, H, K, D, F, V, L = (cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
                           cfg.head_dim, cfg.intermediate_size, cfg.vocab_size,
                           cfg.num_layers)
    rng = np.random.default_rng(0)

    tensors = {
        "model.embed_tokens.weight": rng.standard_normal((V, E), dtype=np.float32) * 0.02,
        "model.norm.weight": np.ones(E, np.float32),
        "lm_head.weight": rng.standard_normal((V, E), dtype=np.float32) * 0.02,
    }
    for i in range(L):
        p = f"model.layers.{i}."
        tensors[p + "input_layernorm.weight"] = np.ones(E, np.float32)
        tensors[p + "post_attention_layernorm.weight"] = np.ones(E, np.float32)
        tensors[p + "self_attn.q_proj.weight"] = rng.standard_normal((H * D, E), dtype=np.float32) * 0.02
        tensors[p + "self_attn.k_proj.weight"] = rng.standard_normal((K * D, E), dtype=np.float32) * 0.02
        tensors[p + "self_attn.v_proj.weight"] = rng.standard_normal((K * D, E), dtype=np.float32) * 0.02
        tensors[p + "self_attn.o_proj.weight"] = rng.standard_normal((E, H * D), dtype=np.float32) * 0.02
        tensors[p + "mlp.gate_proj.weight"] = rng.standard_normal((F, E), dtype=np.float32) * 0.02
        tensors[p + "mlp.up_proj.weight"] = rng.standard_normal((F, E), dtype=np.float32) * 0.02
        tensors[p + "mlp.down_proj.weight"] = rng.standard_normal((E, F), dtype=np.float32) * 0.02

    with tempfile.TemporaryDirectory() as d:
        save_file(tensors, os.path.join(d, "model.safetensors"))
        ecfg = EngineConfig(model=cfg, model_path=d, dtype="float32")
        params = load_hf(ecfg)
        # parity: loaded params reproduce torch-convention linear layers
        inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta, None))
        logits = llama.forward_train(params, cfg, inv_freq, jnp.ones((1, 4), jnp.int32))
        assert bool(jnp.isfinite(logits).all())
        # spot-check a projection: our wq[l] == q_proj.T reshaped
        wq0 = np.asarray(params["layers"]["wq"][0])  # [E, H, D]
        ref = tensors["model.layers.0.self_attn.q_proj.weight"].reshape(H, D, E).transpose(2, 0, 1)
        np.testing.assert_allclose(wq0, ref, atol=1e-6)


# ---- k8s discovery with a fake API ----

def test_service_discovery_add_remove():
    from smg_tpu.gateway.discovery import (
        DiscoveryConfig,
        ServiceDiscovery,
    )
    from smg_tpu.gateway.workers import WorkerRegistry, WorkerType

    class FakeApi:
        def __init__(self):
            self.pods = []

        async def list_pods(self, selector):
            return self.pods

    class FakeClient:
        def __init__(self, url):
            self.url = url

        async def get_model_info(self):
            return {"model_id": "m-disc"}

        async def close(self):
            pass

    def pod(name, ip, role="regular", port=None):
        ann = {}
        if port:
            ann["smg.ai/grpc-port"] = str(port)
        return {
            "metadata": {"name": name, "labels": {"smg.ai/role": role},
                         "annotations": ann},
            "status": {"podIP": ip, "phase": "Running"},
        }

    async def go():
        registry = WorkerRegistry()
        api = FakeApi()
        disc = ServiceDiscovery(
            registry, DiscoveryConfig(), api=api, client_factory=FakeClient
        )
        api.pods = [pod("w0", "10.0.0.1"), pod("w1", "10.0.0.2", role="prefill", port=40001)]
        await disc.sync_once()
        ws = registry.list()
        assert {w.worker_id for w in ws} == {"k8s-w0", "k8s-w1"}
        w1 = registry.get("k8s-w1")
        assert w1.worker_type == WorkerType.PREFILL
        assert w1.url == "10.0.0.2:40001"
        assert w1.model_id == "m-disc"
        # pod disappears -> worker removed
        api.pods = [pod("w0", "10.0.0.1")]
        await disc.sync_once()
        assert registry.get("k8s-w1") is None
        assert registry.get("k8s-w0") is not None

    asyncio.run(go())


# ---- config validation layer (reference: ConfigValidator,
# model_gateway/src/config/validation.rs) ----


def test_validate_engine_config_catches_mesh_mismatches():
    from smg_tpu.config import ConfigError, validate_engine_config
    from smg_tpu.config.validation import raise_on_errors
    from smg_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from smg_tpu.models.config import tiny_test_config

    sched = SchedulerConfig(
        max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
        prefill_token_buckets=(32, 64), decode_batch_buckets=(4,),
    )
    ok = EngineConfig(
        model=tiny_test_config(),
        parallel=ParallelConfig(tp=2),
        cache=CacheConfig(num_pages=64, auto_size=False, dtype="float32"),
        scheduler=sched, dtype="float32",
    )
    assert [i for i in validate_engine_config(ok) if i.severity == "error"] == []

    # tp=3 divides neither heads (8) nor ffn (256 yes, but heads no)
    bad_tp = EngineConfig(
        model=tiny_test_config(), parallel=ParallelConfig(tp=3),
        cache=CacheConfig(num_pages=64, auto_size=False, dtype="float32"),
        scheduler=sched, dtype="float32",
    )
    errs = [i for i in validate_engine_config(bad_tp) if i.severity == "error"]
    assert any("num_heads" in i.message for i in errs)

    # pp=3 does not divide 4 layers
    bad_pp = EngineConfig(
        model=tiny_test_config(), parallel=ParallelConfig(pp=3),
        cache=CacheConfig(num_pages=64, auto_size=False, dtype="float32"),
        scheduler=sched, dtype="float32",
    )
    assert any("num_layers" in str(i) for i in validate_engine_config(bad_pp))

    # ep on a dense model
    bad_ep = EngineConfig(
        model=tiny_test_config(), parallel=ParallelConfig(ep=2),
        cache=CacheConfig(num_pages=64, auto_size=False, dtype="float32"),
        scheduler=sched, dtype="float32",
    )
    assert any("dense" in str(i) for i in validate_engine_config(bad_ep))

    # pool too small for a single max-length sequence -> Engine refuses
    import pytest as _pytest

    bad_pages = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(num_pages=4, auto_size=False, dtype="float32"),
        scheduler=sched, dtype="float32",
    )
    with _pytest.raises(ConfigError):
        raise_on_errors(validate_engine_config(bad_pages))


def test_validate_gateway_config():
    from smg_tpu.config import validate_gateway_config

    assert validate_gateway_config(policy="round_robin", workers=["h:1"]) == []
    assert any(
        i.field == "policy"
        for i in validate_gateway_config(policy="nope")
    )
    # PD requires both legs
    assert any(
        "PD" in i.message
        for i in validate_gateway_config(prefill_workers=["h:1"])
    )
    # unsupported scheme
    assert any(
        "scheme" in i.message
        for i in validate_gateway_config(workers=["ftp://x"])
    )
    # http scheme = proxy transport, valid
    assert validate_gateway_config(workers=["http://x:8000"]) == []
