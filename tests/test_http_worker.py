"""HTTP-proxy router path: OpenAI-wire engine workers behind the gateway
(reference: ``model_gateway/src/routers/http/router.rs``) — registered via
POST /workers with an http:// URL, policy-balanced, health-checked, and
proxied text-level with SSE re-streaming."""

import asyncio
import json
import threading

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.gateway.server import AppContext, build_app


def make_mock_http_worker(seen: list, model_id: str = "proxy-model"):
    """Protocol-accurate OpenAI-compatible engine worker."""

    async def models(request: web.Request):
        return web.json_response({"object": "list", "data": [{"id": model_id}]})

    async def health(request: web.Request):
        return web.Response(text="ok")

    async def chat(request: web.Request):
        body = await request.json()
        seen.append({"path": "/v1/chat/completions", "body": body})
        if body.get("stream"):
            resp = web.StreamResponse(headers={"content-type": "text/event-stream"})
            await resp.prepare(request)
            for frag in ("hel", "lo"):
                f = {"id": "c1", "object": "chat.completion.chunk",
                     "choices": [{"index": 0, "delta": {"content": frag}}]}
                await resp.write(f"data: {json.dumps(f)}\n\n".encode())
            f = {"id": "c1", "object": "chat.completion.chunk",
                 "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}]}
            await resp.write(f"data: {json.dumps(f)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        return web.json_response({
            "id": "c1", "object": "chat.completion", "created": 1,
            "model": body.get("model"),
            "choices": [{"index": 0,
                         "message": {"role": "assistant", "content": "from http worker"},
                         "finish_reason": "stop"}],
            "usage": {"prompt_tokens": 4, "completion_tokens": 3, "total_tokens": 7},
        })

    async def completions(request: web.Request):
        body = await request.json()
        seen.append({"path": "/v1/completions", "body": body})
        return web.json_response({
            "id": "c2", "object": "text_completion", "created": 1,
            "model": body.get("model"),
            "choices": [{"index": 0, "text": " continued", "finish_reason": "stop"}],
        })

    app = web.Application()
    app.router.add_get("/v1/models", models)
    app.router.add_get("/health", health)
    app.router.add_post("/v1/chat/completions", chat)
    app.router.add_post("/v1/completions", completions)
    return app


@pytest.fixture(scope="module")
def proxy_gateway():
    loop = asyncio.new_event_loop()
    seen: list = []
    ctx = AppContext(policy="round_robin")

    async def _setup():
        upstream = TestServer(make_mock_http_worker(seen))
        await upstream.start_server()
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        url = str(upstream.make_url("")).rstrip("/")
        r = await tc.post("/workers", json={"url": url})
        assert r.status == 200, await r.text()
        return tc, upstream

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=60)

    tc, upstream = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.client, h.seen, h.ctx = run, tc, seen, ctx
    yield h
    run(tc.close())
    run(upstream.close())
    loop.call_soon_threadsafe(loop.stop)


def test_http_worker_registration_reports_model(proxy_gateway):
    h = proxy_gateway

    async def go():
        r = await h.client.get("/workers")
        return await r.json()

    body = h.run(go())
    assert len(body["workers"]) == 1
    assert body["workers"][0]["model_id"] == "proxy-model"


def test_http_worker_chat_roundtrip(proxy_gateway):
    h = proxy_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "proxy-model",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 8,
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200
    assert body["choices"][0]["message"]["content"] == "from http worker"
    # request went through the proxy transport, not tokenization
    assert h.seen[-1]["path"] == "/v1/chat/completions"
    assert h.seen[-1]["body"]["messages"][0]["content"] == "hi"
    # registry accounting: guard bumped the counter
    w = h.ctx.registry.list()[0]
    assert w.total_requests >= 1


def test_http_worker_chat_streaming(proxy_gateway):
    h = proxy_gateway

    async def go():
        r = await h.client.post("/v1/chat/completions", json={
            "model": "proxy-model",
            "messages": [{"role": "user", "content": "stream please"}],
            "stream": True,
        })
        return r.status, await r.text()

    status, raw = h.run(go())
    assert status == 200
    frames = [l[6:] for l in raw.splitlines() if l.startswith("data: ")]
    assert frames[-1] == "[DONE]"
    deltas = [json.loads(f) for f in frames[:-1]]
    text = "".join(
        d["choices"][0]["delta"].get("content", "") for d in deltas
    )
    assert text == "hello"


def test_http_worker_completions_proxy(proxy_gateway):
    h = proxy_gateway

    async def go():
        r = await h.client.post("/v1/completions", json={
            "model": "proxy-model", "prompt": "once upon", "max_tokens": 4,
        })
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200
    assert body["choices"][0]["text"] == " continued"
    assert h.seen[-1]["path"] == "/v1/completions"


def test_http_worker_error_maps_to_worker_error(proxy_gateway):
    """A dead HTTP worker surfaces 502 worker_error and feeds the breaker."""
    h = proxy_gateway

    async def go():
        from smg_tpu.gateway.http_worker import HttpWorkerClient
        from smg_tpu.gateway.workers import Worker

        dead = Worker(
            worker_id="dead", model_id="dead-model",
            client=HttpWorkerClient("http://127.0.0.1:9"),  # discard port
        )
        h.ctx.registry.add(dead)
        try:
            r = await h.client.post("/v1/chat/completions", json={
                "model": "dead-model",
                "messages": [{"role": "user", "content": "x"}],
            })
            return r.status, await r.json(), dead.total_failures
        finally:
            h.ctx.registry.remove("dead")

    status, body, failures = h.run(go())
    assert status == 502
    assert body["error"]["type"] == "worker_error"
    assert failures >= 1
