"""Tensor-parallel sharded decode as a first-class runner mode.

Byte-parity: a tp>1 engine on the forced 8-device CPU mesh must emit
token streams BYTE-IDENTICAL to the single-device engine — across the
overlapped pipeline, the megastep horizon, chunked prefill, and fused
speculation, at temperature 0 and 0.8 (same sampling-key fold order, same
program semantics; GSPMD only changes where the math runs).  Logprobs may
differ by float association across shards, bounded at 1e-3.

Hygiene: steady-state decode on the mesh is transfer-guard clean and
0-recompile (DecodeState buffers and every launch upload are committed to
the mesh's replicated sharding — no per-launch resharding), adaptive-K
churn reuses one trace per batch bucket, and sharded traffic leaves a
zero-leak ``Engine.audit()``.

Policy: KV donation is an explicit per-backend/per-mode table
(``engine/donation.py``), not a runner-internal heuristic.
"""

import jax
import numpy as np
import pytest

from smg_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    ParallelConfig,
    SchedulerConfig,
)
from smg_tpu.engine.donation import kv_donation_policy
from smg_tpu.engine.engine import Engine
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer

PROMPT = list(range(5, 30))
# cyclic pattern so the n-gram drafter actually drafts (prompt lookup hits)
SPEC_PROMPT = [17, 40, 61, 17, 52, 61, 17, 40, 61, 17, 52, 61] * 3


def make_engine(parallel=None, devices=None, *, overlap=True, horizon=1,
                horizon_max=0, adaptive=False, spec=False,
                max_prefill_tokens=64, buckets=(32, 64), pages=96,
                max_seq_len=256):
    cfg = EngineConfig(
        model=tiny_test_config(),
        parallel=parallel or ParallelConfig(),
        cache=CacheConfig(page_size=16, num_pages=pages, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=max_seq_len,
            max_prefill_tokens=max_prefill_tokens,
            prefill_token_buckets=buckets, decode_batch_buckets=(4,),
            overlap_schedule=overlap, decode_horizon=horizon,
            decode_horizon_max=horizon_max, adaptive_horizon=adaptive,
            speculative=spec,
        ),
        dtype="float32",
    )
    return Engine(cfg, tokenizer=MockTokenizer(), devices=devices)


def gen(eng, temp=0.0, n=24, prompt=PROMPT):
    return eng.generate(
        prompt_ids=prompt,
        sampling=SamplingParams(temperature=temp, max_new_tokens=n,
                                ignore_eos=True),
    )


def assert_pair(cpu_devices, tp, temp, *, prompt=PROMPT, n=24, **kw):
    ref = gen(make_engine(ParallelConfig(), cpu_devices[:1], **kw),
              temp=temp, n=n, prompt=prompt)
    got = gen(make_engine(ParallelConfig(tp=tp), cpu_devices[:tp], **kw),
              temp=temp, n=n, prompt=prompt)
    assert got.token_ids == ref.token_ids
    np.testing.assert_allclose(got.logprobs, ref.logprobs, atol=1e-3)


# ---- byte-parity vs single-device (fast pairwise slice; full grid: slow)

@pytest.mark.parametrize("overlap,horizon,temp", [
    (True, 1, 0.0),
    (False, 4, 0.8),
    (True, 4, 0.0),
])
def test_tp2_stream_parity(cpu_devices, overlap, horizon, temp):
    assert_pair(cpu_devices, 2, temp, overlap=overlap, horizon=horizon)


@pytest.mark.slow
@pytest.mark.parametrize("overlap", [True, False])
@pytest.mark.parametrize("horizon", [1, 4])
@pytest.mark.parametrize("temp", [0.0, 0.8])
def test_tp2_stream_parity_full_grid(cpu_devices, overlap, horizon, temp):
    assert_pair(cpu_devices, 2, temp, overlap=overlap, horizon=horizon)


def test_tp2_chunked_prefill_parity(cpu_devices):
    """A 96-token prompt under a 32-token per-step budget prefills in
    resumable chunks (non-final chunks through the KV-only extend path);
    the sharded engine must chunk AND sample identically."""
    long_prompt = [(7 * j) % 300 + 5 for j in range(96)]
    assert_pair(
        cpu_devices, 2, 0.0, prompt=long_prompt,
        max_prefill_tokens=32, buckets=(32,), pages=128, max_seq_len=512,
    )


@pytest.mark.parametrize("temp", [0.0, 0.8])
def test_tp2_speculative_parity(cpu_devices, temp):
    """Fused draft-verify on the mesh: same drafts, same acceptance, same
    stream as the single-device spec engine."""
    assert_pair(cpu_devices, 2, temp, prompt=SPEC_PROMPT, n=32, spec=True)


def test_tp4_kv_heads_replication_fallback(cpu_devices):
    """tiny model has 2 kv heads: tp=4 cannot shard the wk/wv head dim and
    must fall back to replicating it (shape-aware tree_shardings) while
    still sharding q/ffn/vocab — and stay byte-identical."""
    assert_pair(cpu_devices, 4, 0.0, horizon=4)


# ---- steady-state hygiene on the full 8-device mesh

def test_tp8_steady_state_guard_clean(cpu_devices):
    """0 recompiles + no implicit transfers at steady state on an 8-device
    mesh: every decode input is either a resident mesh-committed DecodeState
    buffer or an explicit replicated upload."""
    from smg_tpu.analysis.runtime_guards import steady_state_guard

    eng = make_engine(ParallelConfig(tp=8), cpu_devices[:8], horizon=4)
    done = {}
    prompts = [[(7 * i + j) % 90 + 5 for j in range(16)] for i in range(2)]
    for i, p in enumerate(prompts):
        eng.submit(p, SamplingParams(temperature=0.0, max_new_tokens=64,
                                     ignore_eos=True),
                   rid=f"r{i}",
                   on_output=lambda o, i=i: done.setdefault(i, []).append(o))
    for _ in range(8):  # warmup: prefill + prime the pipeline + compiles
        eng.step()
    with steady_state_guard() as cc:
        for _ in range(8):
            eng.step()
    assert cc.count == 0
    while eng.scheduler.has_work():
        eng.step()
    lens = {i: sum(len(o.new_token_ids) for o in v) for i, v in done.items()}
    assert lens == {0: 64, 1: 64}


def test_tp2_adaptive_k_single_trace(cpu_devices):
    """Adaptive-K churn (staggered finishes move the horizon) rides ONE
    compiled trace per batch bucket: K is a device scalar, not a cache key."""
    from smg_tpu.analysis.runtime_guards import steady_state_guard

    eng = make_engine(ParallelConfig(tp=2), cpu_devices[:2],
                      adaptive=True, horizon=2, horizon_max=4)
    done = {}
    lengths = [40, 46, 52, 58]  # finishes land at different horizons
    for i, n in enumerate(lengths):
        eng.submit([(5 * i + j) % 90 + 5 for j in range(16)],
                   SamplingParams(temperature=0.0, max_new_tokens=n,
                                  ignore_eos=True),
                   rid=f"a{i}",
                   on_output=lambda o, i=i: done.setdefault(i, []).append(o))
    for _ in range(10):
        eng.step()
    with steady_state_guard() as cc:
        while eng.scheduler.has_work():
            eng.step()
    assert cc.count == 0
    lens = {i: sum(len(o.new_token_ids) for o in v) for i, v in done.items()}
    assert lens == {i: n for i, n in enumerate(lengths)}


def test_tp2_zero_leak_audit(cpu_devices):
    """Sharded traffic leaves no leaked pages / radix pins / stranded
    frames: the loadgen quiescence contract holds on a mesh."""
    eng = make_engine(ParallelConfig(tp=2), cpu_devices[:2], horizon=2)
    for k in range(3):
        gen(eng, temp=0.8 if k % 2 else 0.0, n=16)
    audit = eng.audit()
    assert audit["quiescent"] is True
    assert audit["clean"] is True
    assert audit["leaked_pages"] == 0


# ---- donation policy (explicit per-backend/per-mode table)

def test_kv_donation_policy_table():
    assert kv_donation_policy("cpu", overlap_active=True).donate_kv is False
    assert kv_donation_policy("cpu", overlap_active=False).donate_kv is True
    assert kv_donation_policy("tpu", overlap_active=True).donate_kv is True
    assert kv_donation_policy("tpu", overlap_active=False).donate_kv is True
    assert kv_donation_policy("gpu", overlap_active=True).donate_kv is True
    # unknown platforms get the accelerator rule (donate), never the CPU
    # special case
    assert kv_donation_policy("neuron", overlap_active=True).donate_kv is True
    p = kv_donation_policy("cpu", overlap_active=True, sharded=True)
    assert p.sharded and "CPU PJRT" in p.reason
    assert "sharded" in p.describe()


def test_runner_resolves_donation_policy(cpu_devices):
    on = make_engine(ParallelConfig(tp=2), cpu_devices[:2], overlap=True)
    off = make_engine(ParallelConfig(tp=2), cpu_devices[:2], overlap=False)
    assert on.runner.donation.donate_kv is False  # CPU + overlap
    assert on.runner.donation.sharded is True
    assert off.runner.donation.donate_kv is True  # sync CPU keeps aliasing


# ---- observability surfaces of the TP runner mode

def test_mesh_surfaces(cpu_devices):
    from smg_tpu.engine.flight_recorder import SCHEMA_VERSION, STEP_RECORD_KEYS

    eng = make_engine(ParallelConfig(tp=2), cpu_devices[:2])
    gen(eng, n=8)
    loads = eng.loads()
    mesh = loads["mesh"]
    assert mesh["devices"] == 2
    assert mesh["shape"]["tp"] == 2
    assert mesh["platform"] == "cpu"
    assert mesh["donate_kv"] is False  # overlap on a CPU mesh
    assert loads["dispatch_enqueue_seconds"] > 0.0
    # flight ring: every step record carries the mesh device count (schema v4)
    assert SCHEMA_VERSION == 4
    assert "mesh" in STEP_RECORD_KEYS
    dump = eng.dump_flight("test")
    recs = dump["ring"]
    assert recs and all(r["mesh"] == 2 for r in recs)
    # metric gauge set at construction
    sample = list(eng.metrics.mesh_devices.collect())[0].samples[0]
    assert sample.value == 2.0


def test_single_device_mesh_surfaces():
    eng = make_engine()
    gen(eng, n=4)
    loads = eng.loads()
    assert loads["mesh"]["devices"] == 1
    dump = eng.dump_flight("test")
    assert all(r["mesh"] == 1 for r in dump["ring"])
