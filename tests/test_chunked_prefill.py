"""Stall-free chunked-prefill scheduling: the per-step prefill budget,
resumable ``PREFILLING`` cursors, policy parity, preemption/abort landing
MID-prefill, and the backlog/stall observability surfaces.

Complements tests/test_overlap.py (which proves overlap/sync byte parity
under the budgeted scheduler); here the focus is the budget mechanics
themselves and the request lifecycle around an interrupted prefill."""

import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.engine.request import RequestStatus
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer

BUDGET = 64


def make_engine(overlap=False, policy="stall-free", num_pages=256,
                max_seq_len=512, prefix_cache=True, **sched_kw) -> Engine:
    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=num_pages, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=8,
            max_seq_len=max_seq_len,
            max_prefill_tokens=BUDGET,
            prefill_token_buckets=(16, 32, 64),
            decode_batch_buckets=(4, 8),
            overlap_schedule=overlap,
            prefill_mix_policy=policy,
            enable_prefix_cache=prefix_cache,
            **sched_kw,
        ),
        dtype="float32",
    )
    return Engine(cfg, tokenizer=MockTokenizer())


def greedy(max_new=8, **kw) -> SamplingParams:
    return SamplingParams(temperature=0.0, max_new_tokens=max_new,
                          ignore_eos=True, **kw)


def run_streams(engine: Engine, jobs: list) -> dict:
    chunks: dict[str, list] = {rid: [] for rid, _, _ in jobs}
    done: set[str] = set()

    def cb(out):
        chunks[out.rid].append(out)
        if out.finished:
            done.add(out.rid)

    for rid, prompt, sampling in jobs:
        engine.submit(prompt, sampling, rid=rid, on_output=cb)
    for _ in range(5000):
        if len(done) == len(jobs):
            while engine.scheduler.has_work():
                engine.step()
            break
        engine.step()
    else:
        raise TimeoutError(f"jobs stuck: {engine.loads()}")
    out = {}
    for rid, _, _ in jobs:
        toks = [t for c in chunks[rid] for t in c.new_token_ids]
        lps = [round(x, 4) for c in chunks[rid] for x in c.logprobs]
        last = chunks[rid][-1]
        out[rid] = (toks, last.finish_reason, lps)
    return out


LONG = list(range(5, 205))  # 200 tokens -> 4 chunks under the 64 budget
SHORT = list(range(300, 340))


def test_budgeted_vs_legacy_greedy_parity():
    """Per-request token streams are byte-identical between budgeted
    (stall-free) and legacy drain-the-queue scheduling at temp 0."""
    jobs = [
        ("long", LONG, greedy(8)),
        ("s0", SHORT, greedy(12)),
        ("s1", list(range(400, 425)), greedy(10)),
    ]
    a = run_streams(make_engine(policy="stall-free"), jobs)
    b = run_streams(make_engine(policy="throughput"), jobs)
    assert a == b, f"budgeted diverged from legacy:\n{a}\nvs\n{b}"


def test_per_step_budget_is_respected():
    """Stall-free: no step computes more than ``max_prefill_tokens`` of
    prefill; legacy: the long prompt's whole remainder lands in one step."""
    for policy, bound in (("stall-free", BUDGET), ("throughput", len(LONG))):
        eng = make_engine(policy=policy)
        eng.submit(LONG, greedy(4), rid="long")
        deltas = []
        last = 0
        for _ in range(40):
            eng.step()
            cur = eng.scheduler.num_prefill_tokens
            deltas.append(cur - last)
            last = cur
            if not eng.scheduler.has_work():
                break
        assert max(deltas) <= bound
        if policy == "throughput":
            assert max(deltas) == len(LONG)  # the drain really is one step
        else:
            assert sum(1 for d in deltas if d) >= 4  # spread across steps


def test_decode_runs_every_step_during_long_prefill():
    """The stall-free core property: while a long prompt chunks in, the
    running lane receives tokens EVERY step — never a multi-chunk gap."""
    eng = make_engine(policy="stall-free")
    got: list = []
    eng.submit(SHORT, greedy(40), rid="s",
               on_output=lambda o: got.append(len(o.new_token_ids)))
    eng.step()  # admit + first decode
    eng.submit(LONG, greedy(4), rid="long")
    sched = eng.scheduler
    while (req := sched.requests.get("long")) is not None \
            and req.status is not RequestStatus.RUNNING and not req.is_finished:
        n_before = len(got)
        eng.step()
        assert len(got) > n_before and got[-1] > 0, \
            "decode lane stalled during chunked prefill"
    while sched.has_work():
        eng.step()


def test_prefilling_cursor_advances_across_steps():
    eng = make_engine(policy="stall-free")
    eng.submit(LONG, greedy(4), rid="long")
    sched = eng.scheduler
    seen = []
    for _ in range(3):
        eng.step()
        req = sched.requests["long"]
        if req.status is RequestStatus.PREFILLING:
            seen.append(req.prefill_pos)
            assert req.seq_len == req.prefill_pos
            assert req.slot is not None  # holds its slot between chunks
    assert seen == [64, 128, 192]
    while sched.has_work():
        eng.step()


@pytest.mark.parametrize("overlap", [False, True])
def test_abort_mid_prefill_releases_pages_and_locks(overlap):
    eng = make_engine(overlap=overlap)
    # prime the radix with a short request so the long one holds a LOCKED
    # radix node through its prefill (the lock-release path under test)
    eng.generate(prompt_ids=LONG[:40], sampling=greedy(2))
    eng.submit(LONG, greedy(8), rid="long")
    eng.step()
    req = eng.scheduler.requests["long"]
    assert req.status is RequestStatus.PREFILLING
    assert req.radix_node is not None  # shared-prefix lock held mid-prefill
    assert eng.abort("long")
    sched = eng.scheduler
    assert all(s is None for s in sched.slots)
    # every page is either back in the pool or (unlocked) in the radix cache
    held = sched.radix.num_cached_pages
    assert sched.pool.free_count + held == eng.runner.spec.num_pages - 1
    # locks released: the idle cache can be flushed completely
    assert eng.flush_cache()
    assert sched.pool.free_count == eng.runner.spec.num_pages - 1
    # and the engine still serves
    r = eng.generate(prompt_ids=SHORT, sampling=greedy(4))
    assert len(r.token_ids) == 4


def test_abort_waiting_over_budget_request():
    """Abort a request still WAITING because the budget never reached it."""
    eng = make_engine(policy="stall-free")
    eng.submit(LONG, greedy(4), rid="long")
    eng.submit(SHORT, greedy(4), rid="w")
    eng.step()  # long takes the whole budget; w still waiting
    assert eng.scheduler.requests["w"].status is RequestStatus.WAITING
    assert eng.abort("w")
    assert "w" not in eng.scheduler.requests
    while eng.scheduler.has_work():
        eng.step()
    assert eng.scheduler.requests.get("long") is None  # long unaffected


@pytest.mark.parametrize("prefix_cache", [True, False])
def test_preempt_mid_prefill_resumes_with_identical_stream(prefix_cache):
    """A mid-prefill preemption victim must produce the SAME final stream as
    an uninterrupted run (greedy).  With the radix cache on, readmission
    resumes from the banked cursor instead of recomputing."""
    ref = run_streams(
        make_engine(prefix_cache=prefix_cache), [("long", LONG, greedy(8))]
    )["long"]

    eng = make_engine(prefix_cache=prefix_cache)
    got: dict = {"long": []}
    eng.submit(LONG, greedy(8), rid="long",
               on_output=lambda o: got["long"].append(o))
    eng.step()
    eng.step()  # two chunks in: cursor at 128
    sched = eng.scheduler
    req = sched.requests["long"]
    assert req.status is RequestStatus.PREFILLING and req.prefill_pos == 128
    sched._preempt(req)  # the path a page-starved decode lane would take
    assert req.status is RequestStatus.PREEMPTED
    assert req.slot is None and not req.owned_pages and not req.shared_pages
    assert req.prefill_pos == 0 and req.seq_len == 0
    if prefix_cache:
        # computed chunks banked for resume: 128 tokens = 8 pages
        assert sched.radix.num_cached_pages >= 8
    while sched.has_work():
        eng.step()
    toks = [t for c in got["long"] for t in c.new_token_ids]
    lps = [round(x, 4) for c in got["long"] for x in c.logprobs]
    assert (toks, got["long"][-1].finish_reason, lps) == ref
    if prefix_cache:
        # readmission resumed from the cursor via a radix hit, not a restart
        assert eng.loads()["cached_prompt_tokens"] >= 128


def test_preemption_under_pressure_lands_mid_prefill():
    """Organic page pressure: a decode lane's growth preempts the PREFILLING
    request; it resumes and completes with a correct stream."""
    # pool sized so the long admission leaves NOTHING free (1 garbage + 3
    # for the short lane + 13 for the long prompt = 17): the short lane's
    # first page-boundary crossing must preempt the prefiller
    eng = make_engine(num_pages=17, max_seq_len=256, watermark_pages=0)
    ref = run_streams(
        make_engine(num_pages=64, max_seq_len=256), [("long", LONG, greedy(6))]
    )["long"]
    got: dict = {"long": [], "s": []}
    # 47-token prompt = 3 pages, crosses into page 4 after one decode step
    eng.submit(list(range(400, 447)), greedy(20), rid="s",
               on_output=lambda o: got["s"].append(o))
    eng.step()
    eng.submit(LONG, greedy(6), rid="long",
               on_output=lambda o: got["long"].append(o))
    sched = eng.scheduler
    saw_prefilling_preempt = False
    for _ in range(400):
        n_pre = sched.num_preemptions
        eng.step()
        # the only preemptible victim is "long" (s is the requester); a
        # preemption before long produced ANY token landed mid-prefill —
        # a RUNNING victim would already have its first sampled token
        if sched.num_preemptions > n_pre and not got["long"]:
            saw_prefilling_preempt = True
        if not sched.has_work():
            break
    assert sched.num_preemptions >= 1
    assert saw_prefilling_preempt, "preemption never landed mid-prefill"
    toks = [t for c in got["long"] for t in c.new_token_ids]
    assert toks == ref[0]
    assert [o.finished for o in got["s"]][-1]


def test_loads_exposes_prefill_backlog():
    eng = make_engine(policy="stall-free")
    eng.submit(LONG, greedy(4), rid="long")
    eng.submit(SHORT, greedy(4), rid="w")
    eng.step()
    loads = eng.loads()
    assert loads["num_prefilling"] == 1
    assert loads["prefill_inflight_tokens"] == len(LONG) - 64
    assert loads["prefill_backlog_tokens"] == (len(LONG) - 64) + len(SHORT)
    # un-prefilled inflight tokens count as queued work for dp routing
    assert loads["queued_tokens"] >= loads["prefill_backlog_tokens"]
    while eng.scheduler.has_work():
        eng.step()
    loads = eng.loads()
    assert loads["num_prefilling"] == 0
    assert loads["prefill_inflight_tokens"] == 0
    assert loads["prefill_backlog_tokens"] == 0


def test_step_and_stall_metrics_exported():
    from prometheus_client import generate_latest

    eng = make_engine(policy="stall-free")
    run_streams(eng, [("long", LONG, greedy(6)), ("s", SHORT, greedy(16))])
    text = generate_latest(eng.metrics.registry).decode()
    assert 'smg_engine_steps_total{kind="mixed"}' in text
    assert "smg_engine_decode_stall_seconds_total" in text
    assert "smg_engine_prefill_inflight_tokens" in text
    # a long prompt admitted beside a decoding lane yields mixed steps and
    # attributes its in-step delay to the stall counter
    for line in text.splitlines():
        if line.startswith('smg_engine_steps_total{kind="mixed"}'):
            assert float(line.split()[-1]) >= 1


def test_partial_chunk_packs_leftover_budget():
    """Two prompts whose combined remainder exceeds the budget: the second
    starts with the leftover as a partial resumable chunk (not deferred)."""
    eng = make_engine(policy="stall-free")
    eng.submit(list(range(5, 53)), greedy(4), rid="a")  # 48 tokens
    eng.submit(list(range(100, 148)), greedy(4), rid="b")  # 48 tokens
    eng.step()
    sched = eng.scheduler
    ra, rb = sched.requests["a"], sched.requests["b"]
    assert ra.status is RequestStatus.RUNNING  # fit the budget, sampled
    assert rb.status is RequestStatus.PREFILLING  # packed the leftover 16
    assert rb.prefill_pos == 16
    while sched.has_work():
        eng.step()
    assert not sched.requests


def test_zero_and_overlong_heads_do_not_burn_budget():
    eng = make_engine(policy="stall-free", max_seq_len=256)
    outs = {}

    def cb(o):
        outs.setdefault(o.rid, []).append(o)

    eng.submit(list(range(5, 300)), greedy(4), rid="toolong", on_output=cb)
    eng.submit(SHORT, SamplingParams(max_new_tokens=0), rid="zero",
               on_output=cb)
    eng.submit(SHORT, greedy(4), rid="ok", on_output=cb)
    eng.step()
    assert outs["toolong"][-1].finish_reason == "error"
    assert outs["zero"][-1].finish_reason == "length"
    # the real request admitted and prefilled in the same step
    assert eng.scheduler.requests["ok"].status is RequestStatus.RUNNING
    while eng.scheduler.has_work():
        eng.step()
    assert outs["ok"][-1].finished
