"""Audio transcriptions (multipart proxy path) + Interactions API
(reference: server.rs:238-311, crates/protocols/src/{transcription,
interactions}.rs; VERDICT r3 missing #9)."""

import asyncio
import io
import threading
import wave

import numpy as np
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.gateway.server import AppContext, build_app
from smg_tpu.gateway.worker_client import InProcWorkerClient
from smg_tpu.gateway.workers import Worker
from smg_tpu.models.config import tiny_test_config
from smg_tpu.tokenizer import MockTokenizer


def _wav_bytes(secs=0.2, rate=16000):
    t = np.arange(int(secs * rate)) / rate
    x = (0.3 * np.sin(2 * np.pi * 440 * t) * 32767).astype("<i2")
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(x.tobytes())
    return buf.getvalue()


class FakeAsrWorker:
    """OpenAI-compatible audio worker double: /v1/models + transcriptions."""

    def __init__(self):
        self.app = web.Application()
        self.app.router.add_get("/v1/models", self.models)
        self.app.router.add_post("/v1/audio/transcriptions", self.transcribe)
        self.requests = []

    async def models(self, request):
        return web.json_response({"data": [{"id": "whisper-x"}]})

    async def transcribe(self, request):
        reader = await request.multipart()
        fields, blob = {}, b""
        async for part in reader:
            if part.name == "file":
                blob = await part.read(decode=False)
            elif part.name:
                fields[part.name] = (await part.read(decode=False)).decode()
        self.requests.append((fields, len(blob)))
        if fields.get("response_format") == "text":
            return web.Response(text="hello from asr", content_type="text/plain")
        return web.json_response({"text": "hello from asr",
                                  "language": fields.get("language")})


@pytest.fixture(scope="module")
def stack():
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=300):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    eng = Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=2, max_seq_len=128, max_prefill_tokens=32,
            prefill_token_buckets=(32,), decode_batch_buckets=(2,),
        ),
        dtype="float32", model_id="tiny-ia",
    ), tokenizer=MockTokenizer())
    ctx = AppContext(policy="round_robin")
    ctx.tokenizers.register("tiny-ia", MockTokenizer(), default=True)
    asr = FakeAsrWorker()

    async def _setup():
        runner = web.AppRunner(asr.app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        asr_port = site._server.sockets[0].getsockname()[1]
        ctx.registry.add(Worker(worker_id="w0", client=InProcWorkerClient(eng),
                                model_id="tiny-ia"))
        from smg_tpu.gateway.http_worker import HttpWorkerClient

        ctx.registry.add(Worker(
            worker_id="asr0",
            client=HttpWorkerClient(f"http://127.0.0.1:{asr_port}"),
            model_id="whisper-x",
        ))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return runner, tc

    runner, tc = run(_setup())

    class H:
        pass

    h = H()
    h.run, h.ctx, h.tc, h.asr = run, ctx, tc, asr
    yield h
    run(tc.close())
    run(runner.cleanup())
    loop.call_soon_threadsafe(loop.stop)
    eng.stop()


def _mp_form(**fields):
    import aiohttp

    form = aiohttp.FormData()
    for k, v in fields.items():
        form.add_field(k, v)
    form.add_field("file", _wav_bytes(), filename="a.wav",
                   content_type="audio/wav")
    return form


def test_transcription_proxies_to_audio_worker(stack):
    h = stack

    async def go():
        r = await h.tc.post("/v1/audio/transcriptions",
                            data=_mp_form(model="whisper-x", language="en"))
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 200, body
    assert body["text"] == "hello from asr"
    assert body["language"] == "en"
    fields, blob_len = h.asr.requests[-1]
    assert fields["model"] == "whisper-x" and blob_len > 1000


def test_transcription_text_format(stack):
    h = stack

    async def go():
        r = await h.tc.post("/v1/audio/transcriptions",
                            data=_mp_form(model="whisper-x",
                                          response_format="text"))
        return r.status, await r.text(), r.content_type

    status, text, ctype = h.run(go())
    assert status == 200 and text == "hello from asr"
    assert ctype == "text/plain"


def test_transcription_501_without_audio_worker(stack):
    h = stack

    async def go():
        r = await h.tc.post("/v1/audio/transcriptions",
                            data=_mp_form(model="tiny-ia"))
        return r.status, await r.json()

    status, body = h.run(go())
    assert status == 501
    assert "worker" in body["error"]["message"]


def test_interactions_roundtrip_and_chaining(stack):
    h = stack

    async def go():
        r1 = await h.tc.post("/v1/interactions", json={
            "model": "tiny-ia", "input": "w5 w6",
            "system_instruction": "w9",
            "generation_config": {"temperature": 0, "max_output_tokens": 5},
        })
        b1 = await r1.json()
        assert r1.status == 200, b1
        # chained turn sees the prior context
        r2 = await h.tc.post("/v1/interactions", json={
            "model": "tiny-ia", "input": "w7",
            "previous_interaction_id": b1["id"],
            "generation_config": {"temperature": 0, "max_output_tokens": 4},
        })
        b2 = await r2.json()
        assert r2.status == 200, b2
        # retrieval + delete
        rg = await h.tc.get(f"/v1/interactions/{b1['id']}")
        bg = await rg.json()
        rd = await h.tc.delete(f"/v1/interactions/{b1['id']}")
        r404 = await h.tc.get(f"/v1/interactions/{b1['id']}")
        return b1, b2, bg, rd.status, r404.status

    b1, b2, bg, del_status, get404 = h.run(go())
    assert b1["object"] == "interaction" and b1["id"].startswith("interaction_")
    from smg_tpu.protocols.interactions import output_text

    assert output_text(b1["outputs"])  # model text present
    assert b1["usage"]["total_output_tokens"] == 5
    assert b2["previous_interaction_id"] == b1["id"]
    # chained prompt included turn 1 (usage grows beyond a single turn)
    assert b2["usage"]["total_input_tokens"] > b1["usage"]["total_input_tokens"]
    assert bg["id"] == b1["id"] and bg["outputs"] == b1["outputs"]
    assert del_status == 200 and get404 == 404


def test_interactions_streaming(stack):
    h = stack

    async def go():
        r = await h.tc.post("/v1/interactions", json={
            "model": "tiny-ia", "input": "w5",
            "stream": True,
            "generation_config": {"temperature": 0, "max_output_tokens": 4},
        })
        return r.status, await r.text()

    status, raw = h.run(go())
    assert status == 200
    import json as _json

    frames = [_json.loads(l[6:]) for l in raw.splitlines()
              if l.startswith("data: ") and l[6:] != "[DONE]"]
    deltas = [f for f in frames if f["type"] == "content_delta"]
    assert deltas and all(f["delta"]["text"] for f in deltas)
    final = [f for f in frames if f["type"] == "interaction_complete"]
    assert final and final[0]["interaction"]["outputs"]
    assert raw.rstrip().endswith("data: [DONE]")


def test_interactions_validation(stack):
    h = stack

    async def go():
        r = await h.tc.post("/v1/interactions", json={"input": "w5"})
        return r.status

    assert h.run(go()) == 400  # neither model nor agent
