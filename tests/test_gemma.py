"""Gemma-2 family support: gelu MLP, (1+w) RMSNorm, scaled embeddings,
post-attention/post-ffn norms, attention/final logit softcaps, custom query
scale.  Reference parity target: the Gemma-2 models the reference routes to
its engines (SURVEY §0 model families)."""

import numpy as np
import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.models.config import ModelConfig, tiny_gemma2_config, tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer


def test_hf_config_parses_gemma2():
    cfg = ModelConfig.from_hf_config({
        "architectures": ["Gemma2ForCausalLM"],
        "vocab_size": 256000, "hidden_size": 2304, "intermediate_size": 9216,
        "num_hidden_layers": 26, "num_attention_heads": 8,
        "num_key_value_heads": 4, "head_dim": 256,
        "query_pre_attn_scalar": 256, "sliding_window": 4096,
        "attn_logit_softcapping": 50.0, "final_logit_softcapping": 30.0,
        "rms_norm_eps": 1e-6, "rope_theta": 10000.0,
    })
    assert cfg.activation == "gelu_tanh"
    assert cfg.rms_unit_offset and cfg.embed_scale and cfg.post_norms
    assert cfg.attn_logit_softcap == 50.0
    assert cfg.final_logit_softcap == 30.0
    assert cfg.query_scale == pytest.approx(1.0 / 16.0)
    assert cfg.sliding_window == 4096
    assert cfg.tie_word_embeddings is True
    # llama configs keep llama semantics
    base = tiny_test_config()
    assert base.activation == "silu" and not base.post_norms


def test_unit_offset_norm():
    import jax.numpy as jnp

    from smg_tpu.ops.norms import rms_norm

    x = jnp.asarray([[1.0, 2.0, 3.0]])
    w = jnp.asarray([0.5, 0.5, 0.5])
    plain = rms_norm(x, w, 1e-6)
    offset = rms_norm(x, w, 1e-6, unit_offset=True)
    np.testing.assert_allclose(np.asarray(offset), np.asarray(plain) * 3.0,
                               rtol=1e-5)
    # zero weight + unit offset = identity scale
    ident = rms_norm(x, jnp.zeros(3), 1e-6, unit_offset=True)
    norm_only = rms_norm(x, jnp.ones(3), 1e-6)
    np.testing.assert_allclose(np.asarray(ident), np.asarray(norm_only),
                               rtol=1e-6)


def test_attention_softcap_bounds_scores():
    import jax
    import jax.numpy as jnp

    from smg_tpu.ops.attention import attention_prefill

    T, K, G, D = 4, 2, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (T, K * G, D)) * 100
    k = jax.random.normal(jax.random.PRNGKey(1), (T, K, D)) * 100
    v = jax.random.normal(jax.random.PRNGKey(2), (T, K, D))
    pos = jnp.arange(T)
    out_plain = attention_prefill(q, k, v, pos, jnp.int32(T), 1.0)
    out_cap = attention_prefill(q, k, v, pos, jnp.int32(T), 1.0, softcap=5.0)
    # with huge logits the uncapped softmax saturates to one-hot; the capped
    # one cannot — outputs must differ
    assert not np.allclose(np.asarray(out_plain), np.asarray(out_cap), atol=1e-3)
    # softcap=None is exactly the plain path
    out_none = attention_prefill(q, k, v, pos, jnp.int32(T), 1.0, softcap=None)
    np.testing.assert_array_equal(np.asarray(out_plain), np.asarray(out_none))


def _gemma_engine() -> Engine:
    return Engine(EngineConfig(
        model=tiny_gemma2_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=256, max_prefill_tokens=32,
            prefill_token_buckets=(16, 32), decode_batch_buckets=(2, 4),
        ),
        dtype="float32", model_id="tiny-gemma2",
    ), tokenizer=MockTokenizer())


def test_gemma2_generates_and_differs_from_llama():
    """Tiny Gemma-2 engine: deterministic generation; the family knobs
    measurably change the computation vs a same-seed llama config."""
    import threading

    def gen(eng, prompt, n=8):
        done = threading.Event()
        acc = []

        def cb(out):
            acc.extend(out.new_token_ids)
            if out.finished:
                done.set()

        eng.submit(prompt, SamplingParams(temperature=0.0, max_new_tokens=n,
                                          ignore_eos=True), on_output=cb)
        for _ in range(300):
            eng.step()
            if done.is_set():
                return list(acc)
        raise TimeoutError

    g = _gemma_engine()
    try:
        prompt = list(range(5, 25))
        a = gen(g, prompt)
        b = gen(g, prompt)
        assert a == b and len(a) == 8
        # chunked prefill path too
        long_prompt = [(i * 3) % 90 + 7 for i in range(50)]
        c = gen(g, long_prompt)
        assert len(c) == 8
        # post-norm params exist and loaded shapes match
        assert "post_attn_norm" in g.runner.params["layers"]
        assert "post_mlp_norm" in g.runner.params["layers"]
        # gemma forces the XLA attention paths (kernels lack softcap)
        assert g.runner._prefill_impl_for(8) == "xla"
        assert g.runner._attn_impl_for(64, 512) == "xla"
    finally:
        g.stop()


def test_final_softcap_bounds_logits():
    import jax
    import jax.numpy as jnp

    from smg_tpu.models import llama

    cfg = tiny_gemma2_config()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    h = jax.random.normal(jax.random.PRNGKey(1), (3, cfg.hidden_size)) * 50
    logits = llama.unembed(params, cfg, h)
    assert float(jnp.max(jnp.abs(logits))) <= cfg.final_logit_softcap + 1e-3


def test_sliding_window_validation():
    """Serving beyond the window is now supported (real per-layer masks);
    what stays rejected is ring/sp composition with windows or softcaps."""
    from smg_tpu.config import validate_engine_config
    from smg_tpu.engine.config import ParallelConfig

    def cfg(par):
        return EngineConfig(
            model=tiny_gemma2_config(),
            parallel=par,
            cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                              dtype="float32"),
            scheduler=SchedulerConfig(
                max_batch_size=2, max_seq_len=8192, max_prefill_tokens=32,
                prefill_token_buckets=(32,), decode_batch_buckets=(2,),
            ),
            dtype="float32",
        )

    # long max_seq_len over a windowed model: fine now
    assert not [i for i in validate_engine_config(cfg(ParallelConfig()))
                if "sliding" in i.message or "window" in i.message]
    issues = validate_engine_config(cfg(ParallelConfig(sp=2)))
    assert any("ring attention" in i.message for i in issues)


def test_gemma_weight_mapping_keys():
    from smg_tpu.models.weights import _hf_key_map

    m = _hf_key_map(tiny_gemma2_config(), 4)
    assert m[("layers", "mlp_norm")].endswith("pre_feedforward_layernorm.weight")
    assert m[("layers", "post_attn_norm")].endswith("post_attention_layernorm.weight")
    assert m[("layers", "post_mlp_norm")].endswith("post_feedforward_layernorm.weight")
    # llama mapping unchanged
    lm = _hf_key_map(tiny_test_config(), 4)
    assert lm[("layers", "mlp_norm")].endswith("post_attention_layernorm.weight")
    assert ("layers", "post_attn_norm") not in lm


def test_sliding_window_attention_masks():
    """Window masks vs a dense reference: only the last `window` keys (incl.
    self) attend; window<=0 means global."""
    import jax
    import jax.numpy as jnp

    from smg_tpu.ops.attention import attention_decode, attention_prefill

    T, K, G, D = 8, 2, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (T, K * G, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (T, K, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (T, K, D))
    pos = jnp.arange(T)

    def dense_ref(window):
        qf = np.asarray(q, np.float64).reshape(T, K, G, D)
        kf, vf = np.asarray(k, np.float64), np.asarray(v, np.float64)
        scores = np.einsum("tkgd,skd->tkgs", qf, kf)
        j = np.arange(T)
        mask = j[None, :] <= np.arange(T)[:, None]
        if window:
            mask &= j[None, :] > np.arange(T)[:, None] - window
        scores = np.where(mask[:, None, None, :], scores, -1e30)
        e = np.exp(scores - scores.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        return np.einsum("tkgs,skd->tkgd", p, vf).reshape(T, K * G, D)

    for w in (3, 5, None):
        got = attention_prefill(
            q, k, v, pos, jnp.int32(T), 1.0,
            window=None if w is None else jnp.int32(w),
        )
        np.testing.assert_allclose(np.asarray(got), dense_ref(w),
                                   rtol=1e-4, atol=1e-5)
    # window == 0 (traced "global") equals no window
    g0 = attention_prefill(q, k, v, pos, jnp.int32(T), 1.0, window=jnp.int32(0))
    np.testing.assert_allclose(np.asarray(g0), dense_ref(None), rtol=1e-5)


def test_layer_window_alternation():
    import jax.numpy as jnp

    from smg_tpu.models.llama import _layer_window

    cfg = tiny_gemma2_config()  # pattern 2, window 4096
    w = [int(_layer_window(cfg, jnp.int32(l))) for l in range(4)]
    assert w == [4096, 0, 4096, 0]  # even sliding, odd global
    assert _layer_window(tiny_test_config(), jnp.int32(0)) is None


def test_sliding_window_serving_beyond_window():
    """Contexts LONGER than the window now serve (the v1 restriction is
    gone): outputs deterministic, and the windowed model differs from the
    same weights with the window disabled (locality is real)."""
    import dataclasses
    import threading

    def eng_for(window):
        model = dataclasses.replace(
            tiny_gemma2_config(), sliding_window=window,
            attn_logit_softcap=None, final_logit_softcap=None,
        )
        return Engine(EngineConfig(
            model=model,
            cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                              dtype="float32"),
            scheduler=SchedulerConfig(
                max_batch_size=2, max_seq_len=256, max_prefill_tokens=64,
                prefill_token_buckets=(32, 64), decode_batch_buckets=(2,),
            ),
            dtype="float32", model_id="tiny-sw",
        ), tokenizer=MockTokenizer())

    def gen(eng, prompt, n=6):
        done = threading.Event()
        acc = []

        def cb(out):
            acc.extend(out.new_token_ids)
            if out.finished:
                done.set()

        eng.submit(prompt, SamplingParams(temperature=0.0, max_new_tokens=n,
                                          ignore_eos=True), on_output=cb)
        for _ in range(300):
            eng.step()
            if done.is_set():
                return list(acc)
        raise TimeoutError

    prompt = [(i * 7) % 90 + 5 for i in range(100)]  # 100 > window 32
    win = eng_for(32)
    glob = eng_for(None)
    try:
        a = gen(win, prompt)
        b = gen(win, prompt)
        assert a == b and len(a) == 6
        c = gen(glob, prompt)
        # beyond-window context: locality must change the computation
        assert a != c
        # within-window prompt: window >= context behaves globally
        short = prompt[:20]
        np.testing.assert_array_equal(gen(win, short), gen(glob, short))
    finally:
        win.stop()
        glob.stop()


def test_train_embed_window_bounds():
    """train/embed paths bound contexts to the window at trace time (their
    shared layer body has no per-layer alternation)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from smg_tpu.models import llama
    from smg_tpu.ops.rope import rope_frequencies

    cfg = tiny_gemma2_config()  # window 4096: tiny T is fine
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    inv = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta, None))
    out = llama.forward_embed(params, cfg, inv, jnp.ones((1, 8), jnp.int32),
                              jnp.asarray([8]))
    assert np.isfinite(np.asarray(out)).all()

    # training path bounds real lengths
    small = dataclasses.replace(cfg, sliding_window=4)
    with pytest.raises(ValueError, match="sliding_window"):
        llama.forward_train(params, small, inv,
                            jnp.ones((1, 8), jnp.int32))


def test_mistral_every_layer_window():
    import jax.numpy as jnp

    from smg_tpu.models.llama import _layer_window

    cfg = ModelConfig.from_hf_config({
        "architectures": ["MistralForCausalLM"],
        "vocab_size": 32000, "hidden_size": 4096, "intermediate_size": 14336,
        "num_hidden_layers": 32, "num_attention_heads": 32,
        "num_key_value_heads": 8, "sliding_window": 4096,
    })
    assert cfg.sliding_window == 4096
    assert cfg.sliding_window_pattern == 0  # every layer windowed
    assert cfg.activation == "silu"  # llama semantics otherwise
    for l in range(4):
        assert int(_layer_window(cfg, jnp.int32(l))) == 4096


def test_pp_rejects_alternating_windows():
    from smg_tpu.config import validate_engine_config
    from smg_tpu.engine.config import ParallelConfig

    cfg = EngineConfig(
        model=tiny_gemma2_config(),
        parallel=ParallelConfig(pp=2),
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=2, max_seq_len=128, max_prefill_tokens=32,
            prefill_token_buckets=(32,), decode_batch_buckets=(2,),
        ),
        dtype="float32",
    )
    issues = validate_engine_config(cfg)
    assert any("alternation" in i.message for i in issues)


def test_qwen3_qk_norm():
    """Qwen3 parses + applies per-head q/k RMSNorm (real Qwen3 checkpoints
    would silently be wrong without it)."""
    import dataclasses
    import threading

    import jax.numpy as jnp

    cfg = ModelConfig.from_hf_config({
        "architectures": ["Qwen3ForCausalLM"],
        "vocab_size": 1000, "hidden_size": 128, "intermediate_size": 256,
        "num_hidden_layers": 2, "num_attention_heads": 8,
        "num_key_value_heads": 2, "head_dim": 16,
    })
    assert cfg.qk_norm is True
    cfg2 = ModelConfig.from_hf_config({
        "architectures": ["Qwen2ForCausalLM"],
        "vocab_size": 1000, "hidden_size": 128, "intermediate_size": 256,
        "num_hidden_layers": 2, "num_attention_heads": 8,
        "num_key_value_heads": 2,
    })
    assert cfg2.qk_norm is False
    moe = ModelConfig.from_hf_config({
        "architectures": ["Qwen3MoeForCausalLM"],
        "vocab_size": 1000, "hidden_size": 128, "intermediate_size": 256,
        "num_hidden_layers": 2, "num_attention_heads": 8,
        "num_key_value_heads": 2, "num_experts": 4,
        "num_experts_per_tok": 2, "moe_intermediate_size": 64,
    })
    assert moe.qk_norm is True and moe.arch == "qwen_moe"

    from smg_tpu.models.weights import _hf_key_map

    m = _hf_key_map(dataclasses.replace(tiny_test_config(), qk_norm=True), 4)
    assert m[("layers", "q_norm")].endswith("self_attn.q_norm.weight")
    assert m[("layers", "k_norm")].endswith("self_attn.k_norm.weight")

    def gen(qk):
        eng = Engine(EngineConfig(
            model=dataclasses.replace(tiny_test_config(), qk_norm=qk),
            cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                              dtype="float32"),
            scheduler=SchedulerConfig(
                max_batch_size=2, max_seq_len=128, max_prefill_tokens=32,
                prefill_token_buckets=(32,), decode_batch_buckets=(2,),
            ),
            dtype="float32", model_id="tiny-q3",
        ), tokenizer=MockTokenizer())
        try:
            assert ("q_norm" in eng.runner.params["layers"]) == qk
            done = threading.Event()
            acc = []

            def cb(out):
                acc.extend(out.new_token_ids)
                if out.finished:
                    done.set()

            eng.submit(list(range(5, 25)),
                       SamplingParams(temperature=0.0, max_new_tokens=6,
                                      ignore_eos=True), on_output=cb)
            for _ in range(200):
                eng.step()
                if done.is_set():
                    return list(acc)
            raise TimeoutError
        finally:
            eng.stop()

    a, b = gen(True), gen(False)
    assert len(a) == 6 and len(b) == 6

    # logits-level oracle: the SAME weights with/without the q/k norm must
    # produce different prefill logits (rms rescaling changes attention)
    import jax

    from smg_tpu.models import llama
    from smg_tpu.ops.rope import rope_frequencies

    qcfg = dataclasses.replace(tiny_test_config(), qk_norm=True)
    params = llama.init_params(qcfg, jax.random.PRNGKey(0))
    inv = jnp.asarray(rope_frequencies(qcfg.head_dim, qcfg.rope_theta, None))
    kc = jnp.zeros((qcfg.num_layers, 8, 16,
                    qcfg.num_kv_heads * qcfg.head_dim), jnp.float32)
    toks = jnp.arange(5, 17, dtype=jnp.int32)
    pt = jnp.arange(1, 3, dtype=jnp.int32)
    lo_q, _, _ = llama.forward_prefill(
        params, qcfg, inv, toks, jnp.int32(0), jnp.int32(12),
        kc, jnp.zeros_like(kc), pt)
    # same params sans the norm application (identity weights exist either way)
    plain_cfg = dataclasses.replace(qcfg, qk_norm=False)
    lo_p, _, _ = llama.forward_prefill(
        params, plain_cfg, inv, toks, jnp.int32(0), jnp.int32(12),
        jnp.zeros_like(kc), jnp.zeros_like(kc), pt)
    assert not np.allclose(np.asarray(lo_q), np.asarray(lo_p), atol=1e-4)
