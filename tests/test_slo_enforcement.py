"""SLO enforcement layer + loadgen harness (ISSUE 14).

Covers: declarative ``SloSpec`` parsing, the fast/slow burn-rate window
math (agreement, empty-window behavior, min-requests gating), verdict
flapping hysteresis and edge-triggered violation counting, the SLO-record
termination fix for clients that disconnect between a 429 failover and
first token, the ``Engine.audit()`` zero-leak surface (incl. the
abort-frees-pages-within-one-step contract), the ``/debug/slo/verdicts``
endpoint end to end over an in-proc gateway, and the seeded loadgen smoke
run (small matrix, 2 workers) — tier-1's copy of the CI §9 scenario.
"""

import asyncio
import importlib.util
import pathlib
import threading

import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.faults import FAULTS
from smg_tpu.gateway.observability import Metrics
from smg_tpu.gateway.slo_enforcement import (
    SloEnforcer,
    SloSpec,
    load_slo_specs,
)
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.tokenizer import MockTokenizer

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    FAULTS.clear()


def _load_loadgen():
    import sys

    spec = importlib.util.spec_from_file_location(
        "smg_loadgen", REPO / "benches" / "loadgen.py"
    )
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolve string annotations via sys.modules[cls.__module__]
    sys.modules["smg_loadgen"] = mod
    spec.loader.exec_module(mod)
    return mod


# ---- spec parsing ----


def test_spec_rejects_unknown_keys_and_dead_specs():
    with pytest.raises(ValueError, match="unknown SloSpec key"):
        SloSpec.from_dict({"name": "x", "ttft_p95": 1.0})  # typo'd key
    with pytest.raises(ValueError, match="no targets"):
        SloSpec.from_dict({"name": "x"})
    with pytest.raises(ValueError, match="fast_window_s"):
        SloSpec(name="x", ttft_p95_s=1.0, fast_window_s=60, slow_window_s=30)
    with pytest.raises(ValueError, match="deadline_miss_budget"):
        SloSpec(name="x", deadline_miss_budget=0.0)


def test_load_slo_specs_shapes(tmp_path):
    specs = load_slo_specs([{"name": "a", "ttft_p95_s": 1.0}])
    assert [s.name for s in specs] == ["a"]
    specs = load_slo_specs('{"slos": [{"name": "b", "e2e_p95_s": 2.0}]}')
    assert specs[0].e2e_p95_s == 2.0
    p = tmp_path / "slo.json"
    p.write_text('[{"name": "c", "goodput_ratio_floor": 0.9}]')
    assert load_slo_specs(str(p))[0].goodput_ratio_floor == 0.9
    with pytest.raises(ValueError, match="duplicate"):
        load_slo_specs([{"name": "d", "ttft_p95_s": 1.0},
                        {"name": "d", "ttft_p95_s": 2.0}])


def test_cli_accepts_slo_spec_flag(tmp_path):
    from smg_tpu.cli import build_parser

    p = tmp_path / "slo.json"
    p.write_text('[{"name": "prod", "ttft_p95_s": 0.5}]')
    args = build_parser().parse_args(
        ["launch", "--slo-spec", str(p), "--port", "0"]
    )
    assert args.slo_spec == str(p)
    assert load_slo_specs(args.slo_spec)[0].name == "prod"


# ---- burn-rate window math (stub tracker: ages are controlled) ----


def _rec(age_s=0.0, ttft=0.01, itl=0.002, e2e=0.05, deadline=5.0, met=True,
         voluntary=False, tokens=4):
    return age_s, {
        "ttft_s": ttft, "itl_mean_s": itl, "e2e_s": e2e,
        "deadline_s": deadline, "deadline_met": met, "voluntary": voluntary,
        "output_tokens": tokens,
    }


class _StubTracker:
    """window_records by synthetic record age — time-travel for the math."""

    def __init__(self, aged_records):
        self.aged = aged_records

    def window_records(self, window_secs, now=None):
        return [r for age, r in self.aged if age <= window_secs]


def _enforcer(aged, spec_kw, metrics=None):
    enf = SloEnforcer(metrics=metrics, tracker=_StubTracker(aged))
    enf.install([{"name": "t", "fast_window_s": 10.0, "slow_window_s": 100.0,
                  "min_requests": 2, "hysteresis": 1, **spec_kw}])
    return enf


def test_burn_rate_fast_slow_agreement():
    """Sustained misses land in BOTH windows -> identical burn, verdict
    fails; the burn number itself is miss_fraction / budget."""
    aged = [_rec(age_s=a, met=(i % 2 == 0)) for i, a in
            enumerate((1, 2, 3, 4, 50, 60, 70, 80))]
    enf = _enforcer(aged, {"deadline_miss_budget": 0.25})
    v = enf.evaluate()["verdicts"][0]
    fast, slow = v["windows"]["fast"], v["windows"]["slow"]
    assert fast["violating"] and slow["violating"]
    assert fast["miss_fraction"] == 0.5 and slow["miss_fraction"] == 0.5
    assert fast["burn_rate"] == slow["burn_rate"] == 2.0  # 0.5 / 0.25
    assert v["verdict"] == "fail"


def test_fast_only_violation_does_not_fail():
    """A recent blip with a healthy long window must NOT flip the verdict —
    the multiwindow rule requires sustained violation."""
    aged = (
        [_rec(age_s=a, met=False) for a in (1, 2)]       # bad, recent
        + [_rec(age_s=a, met=True) for a in range(20, 96, 4)]  # long healthy
    )
    enf = _enforcer(aged, {"deadline_miss_budget": 0.3})
    v = enf.evaluate()["verdicts"][0]
    assert v["windows"]["fast"]["violating"]
    assert not v["windows"]["slow"]["violating"]
    assert v["candidate"] == "pass" and v["verdict"] == "pass"


def test_empty_window_behavior():
    """No records: insufficient, zero burn, no breaches, verdict pass —
    an idle gateway is not in violation."""
    enf = _enforcer([], {"ttft_p95_s": 0.001, "deadline_miss_budget": 0.01})
    out = enf.evaluate()
    v = out["verdicts"][0]
    for w in v["windows"].values():
        assert w["requests"] == 0 and not w["sufficient"]
        assert w["burn_rate"] == 0.0 and w["breaches"] == []
    assert out["all_pass"]


def test_min_requests_gates_thin_windows():
    aged = [_rec(age_s=1, ttft=9.0, met=False)]  # one terrible request
    enf = _enforcer(aged, {"ttft_p95_s": 0.1, "deadline_miss_budget": 0.01})
    v = enf.evaluate()["verdicts"][0]
    assert not v["windows"]["fast"]["sufficient"]
    assert v["verdict"] == "pass"


def test_burn_breach_requires_min_deadline_requests():
    """Review fix: the burn breach gates on DEADLINE-CARRYING requests —
    one missed deadline among deadline-less traffic (miss_fraction 1.0)
    must not page anyone, even though the window as a whole is
    'sufficient'."""
    aged = ([_rec(age_s=1, deadline=None) for _ in range(7)]
            + [_rec(age_s=1, met=False)])
    enf = _enforcer(aged, {"deadline_miss_budget": 0.1})
    v = enf.evaluate()["verdicts"][0]
    fast = v["windows"]["fast"]
    assert fast["sufficient"] and fast["with_deadline"] == 1
    assert fast["burn_rate"] == 10.0  # observable, but not actionable alone
    assert "deadline_miss_budget" not in fast["breaches"]
    assert v["verdict"] == "pass"


def test_percentile_targets_breach_by_name():
    aged = [_rec(age_s=1, ttft=5.0, itl=1.0, e2e=9.0, tokens=0)
            for _ in range(4)]
    enf = _enforcer(aged, {"ttft_p95_s": 1.0, "itl_p95_s": 0.5,
                           "e2e_p95_s": 2.0, "goodput_ratio_floor": 0.9})
    v = enf.evaluate()["verdicts"][0]
    # zero tokens -> goodput vacuously 1.0, so the floor must NOT breach
    assert set(v["windows"]["fast"]["breaches"]) == {
        "ttft_p95_s", "itl_p95_s", "e2e_p95_s"
    }


def test_verdict_flapping_hysteresis_and_violation_edges():
    """hysteresis=2: a boundary flapping pass/fail per evaluation never
    flips the verdict; two consecutive disagreements do.  The violations
    counter increments on window onset EDGES, not per evaluation."""
    metrics = Metrics()
    bad = [_rec(age_s=1, met=False) for _ in range(4)]
    good = [_rec(age_s=1, met=True) for _ in range(4)]
    tracker = _StubTracker(bad)
    enf = SloEnforcer(metrics=metrics, tracker=tracker)
    enf.install([{"name": "flap", "deadline_miss_budget": 0.1,
                  "fast_window_s": 10, "slow_window_s": 100,
                  "min_requests": 2, "hysteresis": 2}])

    def counter(window):
        for fam in metrics.registry.collect():
            for s in fam.samples:
                if (s.name == "smg_slo_violations_total"
                        and s.labels.get("window") == window):
                    return s.value
        return 0.0

    # flap: bad, good, bad, good ... verdict must stay pass throughout
    for i in range(4):
        tracker.aged = bad if i % 2 == 0 else good
        v = enf.evaluate()["verdicts"][0]
        assert v["verdict"] == "pass", f"flipped on flap iteration {i}"
    # each bad evaluation after a good one is a fresh onset: 2 edges so far
    assert counter("fast") == 2.0
    # sustained: two consecutive bad evaluations flip it
    tracker.aged = bad
    assert enf.evaluate()["verdicts"][0]["verdict"] == "pass"  # streak 1
    v = enf.evaluate()["verdicts"][0]
    assert v["verdict"] == "fail"  # streak 2 -> flip
    # still-violating re-evaluations do NOT count new violations
    assert counter("fast") == 3.0
    enf.evaluate()
    assert counter("fast") == 3.0
    # sustained recovery flips back after hysteresis evaluations
    tracker.aged = good
    assert enf.evaluate()["verdicts"][0]["verdict"] == "fail"
    assert enf.evaluate()["verdicts"][0]["verdict"] == "pass"


def test_tracker_window_records_filters_by_age():
    import time as _time

    m = Metrics()
    r = m.slo.begin("old")
    r.first_token(4, 0)
    r.finish("stop")
    now = _time.perf_counter()
    assert len(m.slo.window_records(60.0, now=now)) == 1
    # a "now" far in the future ages the record out of the window
    assert m.slo.window_records(1.0, now=now + 100.0) == []


# ---- SLO record termination on client disconnect (regression) ----


class _QueueFullClient:
    """Always rejects with backpressure after a short dispatch delay."""

    proxy_mode = False

    async def generate(self, req):
        from smg_tpu.gateway.worker_client import WorkerQueueFullError

        await asyncio.sleep(0.01)
        raise WorkerQueueFullError("induced")
        yield  # pragma: no cover

    async def abort(self, rid):
        return True

    async def close(self):
        pass


class _NeverFirstTokenClient:
    """Accepts the dispatch but never produces a first token."""

    proxy_mode = False

    def __init__(self):
        self.dispatched = asyncio.Event()

    async def generate(self, req):
        self.dispatched.set()
        await asyncio.Event().wait()
        yield  # pragma: no cover

    async def abort(self, rid):
        return True

    async def close(self):
        pass


class _FailingClient:
    """Generic dispatch failure (drives the retry-backoff path)."""

    proxy_mode = False

    async def generate(self, req):
        await asyncio.sleep(0.01)
        raise RuntimeError("boom")
        yield  # pragma: no cover

    async def abort(self, rid):
        return True

    async def close(self):
        pass


def _router_with(clients):
    from smg_tpu.gateway.router import Router, RouterConfig
    from smg_tpu.gateway.workers import Worker, WorkerRegistry
    from smg_tpu.policies import PolicyRegistry
    from smg_tpu.tokenizer.registry import TokenizerRegistry

    registry = WorkerRegistry()
    for i, c in enumerate(clients):
        registry.add(Worker(worker_id=f"w{i}", client=c, model_id="m"))
    metrics = Metrics()
    router = Router(
        registry, PolicyRegistry(default="round_robin"), TokenizerRegistry(),
        config=RouterConfig(request_timeout_secs=5.0), metrics=metrics,
    )
    return router, metrics


def _deadline_counts(metrics):
    met = missed = 0.0
    for fam in metrics.registry.collect():
        for s in fam.samples:
            if s.name == "smg_request_deadline_outcomes_total":
                if s.labels.get("outcome") == "met":
                    met = s.value
                elif s.labels.get("outcome") == "missed":
                    missed = s.value
    return met, missed


def _cancelled_execute(router, cancel_after: float):
    from smg_tpu.policies import RequestContext

    async def go():
        async def consume():
            ctx = RequestContext(model_id="m", request_id="r1")
            async for _ev in router._execute(
                ctx, [1, 2, 3], SamplingParams(max_new_tokens=4), "r1", None
            ):
                pass

        task = asyncio.create_task(consume())
        await asyncio.sleep(cancel_after)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass

    asyncio.run(go())


def test_disconnect_between_429_failover_and_first_token_not_a_miss():
    """Regression (ISSUE 14 satellite): a streaming client that disconnects
    AFTER a queue-full failover but BEFORE the first token must terminate
    its SloRequest as a VOLUNTARY ending — one ring record, zero deadline
    outcomes — never leak or land as a phantom deadline miss."""
    hang = _NeverFirstTokenClient()
    router, metrics = _router_with([_QueueFullClient(), hang])
    _cancelled_execute(router, cancel_after=0.15)
    s = metrics.slo.summary()
    assert s["window_requests"] == 1, (
        "disconnect at the failover seam must still terminate the SLO record"
    )
    rec = s["recent"][-1]
    assert rec["voluntary"] is True and rec["deadline_met"] is False
    assert s["deadline"] == {"with_deadline": 0, "met": 0, "missed": 0}
    assert _deadline_counts(metrics) == (0.0, 0.0)
    assert hang.dispatched.is_set(), "failover never reached the second worker"


def test_disconnect_during_retry_backoff_terminates_record():
    """The other half of the seam: cancellation during the retry BACKOFF
    sleep is raised inside an except handler, bypassing the loop's own
    GeneratorExit/CancelledError arm — only the termination backstop
    records it.  Pre-fix this leaked the handle (no ring record at all)."""
    router, metrics = _router_with([_FailingClient(), _FailingClient()])
    # first dispatch fails at ~10ms, then backoff sleeps 100ms: cancel lands
    # inside the sleep
    _cancelled_execute(router, cancel_after=0.05)
    s = metrics.slo.summary()
    assert s["window_requests"] == 1, (
        "cancellation during retry backoff leaked the SLO record"
    )
    assert s["recent"][-1]["voluntary"] is True
    assert _deadline_counts(metrics) == (0.0, 0.0)


# ---- Engine.audit (zero-leak quiescence surface) ----


def make_engine(**sched_kw) -> Engine:
    sched = dict(
        max_batch_size=4, max_seq_len=128, max_prefill_tokens=32,
        prefill_token_buckets=(16, 32, 64), decode_batch_buckets=(4,),
    )
    sched.update(sched_kw)
    return Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(**sched),
        dtype="float32", model_id="tiny-slo",
        flight_dump_min_interval_secs=0.0,
    ), tokenizer=MockTokenizer())


def test_engine_audit_clean_after_traffic_and_rides_loads():
    eng = make_engine()
    for prompt in ([5, 6, 7], list(range(2, 40))):
        eng.generate(prompt_ids=prompt, sampling=SamplingParams(
            temperature=0.0, max_new_tokens=4, ignore_eos=True))
    a = eng.audit()
    assert a["quiescent"] and a["clean"]
    assert a["leaked_pages"] == 0 and a["radix_lock_refcounts"] == 0
    assert a["pending_callbacks"] == 0 and a["inflight_frames"] == 0
    # every allocatable page is free or radix-cached at quiescence
    assert a["free_pages"] + a["radix_cached_pages"] == a["allocatable_pages"]
    # the same verdict rides loads() (and therefore /scheduler)
    loads = eng.loads()
    assert loads["audit"]["clean"] is True
    # hot callers can skip the audit walk
    assert "audit" not in eng.loads(include_audit=False)
    eng.stop()


def test_engine_audit_mid_flight_sees_pins_but_no_leaks():
    eng = make_engine()
    outs: dict = {}
    # 36-token prompt -> 2 full pages bank into the radix cache; the second
    # request shares them, pinning the chain
    base = list(range(2, 38))
    eng.generate(prompt_ids=base, sampling=SamplingParams(
        temperature=0.0, max_new_tokens=2, ignore_eos=True))
    eng.submit(base + [40, 41], SamplingParams(
        temperature=0.0, max_new_tokens=8, ignore_eos=True), rid="x",
        on_output=lambda o: outs.setdefault("x", []).append(o))
    eng.step()
    a = eng.audit()
    assert not a["quiescent"] and a["live_slots"] == 1
    assert a["leaked_pages"] == 0 and a["clean"], a
    assert a["radix_lock_refcounts"] > 0  # the shared prefix is pinned
    assert a["pinned_shared_pages"] >= 2
    while not (outs.get("x") and outs["x"][-1].finished):
        eng.step()
    fin = eng.audit()
    assert fin["quiescent"] and fin["clean"]
    assert fin["radix_lock_refcounts"] == 0
    eng.stop()


def test_aborted_lane_frees_pages_within_one_step():
    """ISSUE 14 satellite (disconnect hardening): an aborted RUNNING lane's
    slot, pages, and radix locks are released by the abort itself — at most
    one step later the audit is clean.  Driven through the public abort
    path (what a client disconnect triggers via the router), no
    monkeypatching."""
    eng = make_engine()
    outs: dict = {}
    free_before = eng.audit()["free_pages"]
    eng.submit(list(range(2, 38)), SamplingParams(
        temperature=0.0, max_new_tokens=64, ignore_eos=True), rid="gone",
        on_output=lambda o: outs.setdefault("gone", []).append(o))
    for _ in range(3):
        eng.step()
    assert eng.audit()["live_slots"] == 1
    assert eng.abort("gone") is True
    eng.step()  # the one allowed step
    a = eng.audit()
    assert a["quiescent"] and a["clean"], a
    assert a["leaked_pages"] == 0 and a["radix_lock_refcounts"] == 0
    # pages returned: free + newly-banked radix pages cover what it held
    assert a["free_pages"] + a["radix_cached_pages"] >= free_before
    eng.stop()


def test_worker_stream_fault_disconnect_excluded_and_clean():
    """The faults.py seam doubles as the disconnect fault test: a
    worker.stream fault kills the transport mid-stream; the engine-side
    lane aborts, pages free, and the gateway SLO layer must not count a
    deadline outcome for it (the router surfaces it as a worker error or
    abandoned stream, both non-goodput)."""
    eng = make_engine()
    a0 = eng.audit()
    assert a0["clean"]
    FAULTS.arm("worker.stream", mode="after", n=2, match="die-me")

    from smg_tpu.gateway.worker_client import (
        InProcWorkerClient,
        WorkerGenerateRequest,
    )

    client = InProcWorkerClient(eng)

    async def go():
        req = WorkerGenerateRequest(
            rid="die-me", input_ids=[5, 6, 7],
            sampling=SamplingParams(temperature=0.0, max_new_tokens=32,
                                    ignore_eos=True))
        try:
            async for _ in client.generate(req):
                pass
        except Exception:
            await client.abort("die-me")

    asyncio.run(go())
    import time as _time

    deadline = _time.monotonic() + 10
    while _time.monotonic() < deadline:
        a = eng.audit()
        if a["quiescent"] and a["clean"]:
            break
        _time.sleep(0.05)
    assert a["quiescent"] and a["clean"], a
    FAULTS.clear()
    eng.stop()


# ---- /debug/slo/verdicts end to end + injected violation dump fetch ----


def test_slo_verdicts_endpoint_and_violation_dump_fetch():
    from aiohttp.test_utils import TestClient, TestServer

    from smg_tpu.gateway.server import AppContext, build_app
    from smg_tpu.gateway.worker_client import InProcWorkerClient
    from smg_tpu.gateway.workers import Worker

    eng = make_engine()
    ctx = AppContext(policy="round_robin", slo_specs=[{
        "name": "tier1", "ttft_p95_s": 30.0, "goodput_ratio_floor": 0.2,
        "deadline_miss_budget": 0.9, "min_requests": 1, "hysteresis": 1,
    }], request_timeout_secs=60.0)
    ctx.tokenizers.register("tiny-slo", MockTokenizer(), default=True)

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=180):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout)

    async def _setup():
        ctx.registry.add(Worker(worker_id="w0", client=InProcWorkerClient(eng),
                                model_id="tiny-slo"))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return tc

    tc = run(_setup())
    try:
        async def drive():
            r = await tc.post("/v1/chat/completions", json={
                "model": "tiny-slo",
                "messages": [{"role": "user", "content": "w5 w6 w7"}],
                "max_tokens": 4, "temperature": 0, "ignore_eos": True,
            })
            assert r.status == 200
            rv = await tc.get("/debug/slo/verdicts")
            return rv.status, await rv.json()

        status, body = run(drive())
        assert status == 200 and body["schema_version"] == 1
        assert body["all_pass"] is True
        (v,) = body["verdicts"]
        assert v["slo"] == "tier1" and v["verdict"] == "pass"
        assert v["windows"]["fast"]["requests"] >= 1

        # ?recent= bounds the /debug/slo per-request slice (review fix: the
        # loadgen reads the WHOLE ring via recent=256 for exact tiling)
        async def slo_slices():
            r1 = await tc.get("/debug/slo", params={"recent": "0"})
            r2 = await tc.get("/debug/slo", params={"recent": "256"})
            return await r1.json(), await r2.json()

        s0, s_all = run(slo_slices())
        assert s0["recent"] == []
        assert len(s_all["recent"]) == s_all["window_requests"]

        # inject a violation window: impossible TTFT target -> verdict
        # fails -> a flight-recorder dump is fetchable for the window
        ctx.metrics.slo_enforcer.install([{
            "name": "injected", "ttft_p95_s": 1e-9,
            "min_requests": 1, "hysteresis": 1,
        }])

        async def violated():
            rv = await tc.get("/debug/slo/verdicts")
            body = await rv.json()
            fr = await tc.get("/debug/flight/w0",
                              params={"reason": "slo_violation"})
            return body, fr.status, await fr.json()

        body, fstatus, fbody = run(violated())
        injected = next(v for v in body["verdicts"] if v["slo"] == "injected")
        assert injected["verdict"] == "fail"
        assert "ttft_p95_s" in injected["windows"]["fast"]["breaches"]
        assert not body["all_pass"]
        assert fstatus == 200 and "schema_version" in fbody["dump"]
        # the violation onset landed in the metric family
        count = 0.0
        for fam in ctx.metrics.registry.collect():
            for s in fam.samples:
                if (s.name == "smg_slo_violations_total"
                        and s.labels.get("slo") == "injected"):
                    count += s.value
        assert count >= 2.0  # fast + slow onsets
    finally:
        run(tc.close())
        loop.call_soon_threadsafe(loop.stop)
        eng.stop()


# ---- the seeded loadgen smoke (tier-1 copy of CI §9) ----


def test_loadgen_smoke_small_matrix():
    """Small mixed matrix (all scenarios at half scale), 2 in-proc workers,
    seeded: every epilogue check must pass — SLO verdicts, goodput floor,
    disconnect exclusion, router band, 429-without-breaker-penalty,
    drain-under-load, zero-leak audits, and the injected-violation flight
    dump."""
    lg = _load_loadgen()
    cfg = lg.LoadgenConfig(seed=0, workers=2, scale=0.5, rate_rps=40.0)
    results = lg.run(cfg)
    failed = {k: c for k, c in results["checks"].items() if not c["ok"]}
    assert results["ok"], f"loadgen checks failed: {failed}"
    # deterministic step-count spot checks (temp 0, ignore_eos, fixed seed)
    sc = results["scenarios"]
    assert sc["short_chat"]["completed"] == sc["short_chat"]["requests"]
    assert sc["zipf_session"]["output_tokens"] == 2 * sc["zipf_session"]["requests"]
    assert sc["stream_disconnect"]["disconnected"] > 0
    assert results["verdicts"]["all_pass"]
    audits = results["audit"]["engines"]
    assert all(a["leaked_pages"] == 0 for a in audits.values())
