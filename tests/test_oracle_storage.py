"""Oracle storage backend + schema remapping (VERDICT r4 missing #6:
``oracle_migrations.rs`` + ``schema.rs`` analogs).  The wire client is a
sqlite-backed fake speaking the Oracle dialect surface the backend emits
(VARCHAR2/CLOB/BINARY_DOUBLE DDL, sequences + NEXTVAL, FETCH FIRST,
ORA-00955 on duplicate DDL, UPPERCASE row keys)."""

import asyncio
import re
import sqlite3

import pytest

from smg_tpu.storage import ConversationItem, StoredResponse
from smg_tpu.storage.oracle import OracleStorage
from smg_tpu.storage.schema import SchemaConfig


class FakeOracle:
    """Dialect-shimmed sqlite standing in for Oracle; also records every
    SQL statement for dialect assertions."""

    def __init__(self):
        self.db = sqlite3.connect(":memory:", check_same_thread=False)
        self.seqs: dict[str, int] = {}
        self.sql_log: list[str] = []

    def _nextval(self, m: re.Match) -> str:
        name = m.group(1)
        self.seqs[name] = self.seqs.get(name, 0) + 1
        return str(self.seqs[name])

    async def query(self, sql: str):
        self.sql_log.append(sql)
        s = sql.strip()
        if re.match(r"CREATE SEQUENCE (\w+)", s, re.I):
            name = re.match(r"CREATE SEQUENCE (\w+)", s, re.I).group(1)
            if name in self.seqs:
                raise RuntimeError(f"ORA-00955: name is already used ({name})")
            self.seqs[name] = 0
            return []
        # dialect shims sqlite understands
        s = (s.replace("VARCHAR2(64)", "TEXT").replace("VARCHAR2(256)", "TEXT")
             .replace("VARCHAR2(32)", "TEXT").replace("BINARY_DOUBLE", "REAL")
             .replace("NUMBER(19)", "INTEGER").replace("NUMBER(10)", "INTEGER")
             .replace("CLOB", "TEXT"))
        s = re.sub(r"FETCH FIRST (\d+) ROWS ONLY", r"LIMIT \1", s)
        s = re.sub(r"(\w+)\.NEXTVAL", self._nextval, s)
        cur = self.db.cursor()
        try:
            cur.execute(s)
        except sqlite3.OperationalError as e:
            msg = str(e)
            if "already exists" in msg:
                raise RuntimeError(f"ORA-00955: name is already used ({msg})")
            raise
        self.db.commit()
        if cur.description is None:
            return []
        cols = [d[0].upper() for d in cur.description]  # oracle canon
        return [dict(zip(cols, row)) for row in cur.fetchall()]


async def _roundtrip(s: OracleStorage):
    conv = await s.create_conversation({"topic": "x"})
    got = await s.get_conversation(conv.id)
    assert got.metadata == {"topic": "x"}
    await s.update_conversation(conv.id, {"y": 1})
    assert (await s.get_conversation(conv.id)).metadata == {"topic": "x", "y": 1}

    items = [
        ConversationItem(type="message", role="user", content={"content": "hi"}),
        ConversationItem(type="message", role="assistant", content={"content": "yo"}),
    ]
    await s.add_items(conv.id, items)
    got_items = await s.list_items(conv.id)
    assert [i.role for i in got_items] == ["user", "assistant"]
    assert (await s.get_item(conv.id, got_items[0].id)).content == {"content": "hi"}
    assert await s.delete_item(conv.id, got_items[0].id)
    assert len(await s.list_items(conv.id)) == 1

    r1 = await s.store_response(StoredResponse(model="m", output=[{"type": "message"}]))
    r2 = await s.store_response(StoredResponse(model="m", previous_response_id=r1.id))
    chain = await s.response_chain(r2.id)
    assert [r.id for r in chain] == [r1.id, r2.id]
    assert await s.delete_response(r1.id)
    assert await s.get_conversation("nope") is None
    assert await s.delete_conversation(conv.id)
    assert await s.get_conversation(conv.id) is None


def test_oracle_roundtrip_default_schema():
    fake = FakeOracle()
    s = OracleStorage(fake)
    asyncio.run(_roundtrip(s))
    ddl = [x for x in fake.sql_log if x.startswith("CREATE TABLE")]
    assert any("VARCHAR2" in x and "CLOB" in x for x in ddl)
    assert any("FETCH FIRST" in x for x in fake.sql_log)
    assert any(".NEXTVAL" in x for x in fake.sql_log)


def test_oracle_migrations_are_versioned_and_rerun_safe():
    fake = FakeOracle()
    s = OracleStorage(fake)

    async def go():
        await s._ensure()
        rows = await fake.query("SELECT MAX(version) AS v FROM smg_migrations")
        assert rows[0]["V"] == 3  # three migration batches applied
        # a second instance on the same DB replays cleanly (ORA-00955
        # absorbed) and does NOT re-bump versions
        s2 = OracleStorage(fake)
        await s2._ensure()
        rows = await fake.query("SELECT COUNT(*) AS c FROM smg_migrations")
        assert rows[0]["C"] == 3

    asyncio.run(go())


def test_oracle_insert_never_splices_client_strings():
    """A client-controlled value ending in ".NEXTVAL" (the old raw-splice
    sentinel) must be quoted like any other string — only the module-private
    _RawSql wrapper goes in verbatim."""
    from smg_tpu.storage.oracle import _RawSql

    fake = FakeOracle()
    s = OracleStorage(fake)
    hostile = "evil.NEXTVAL"

    async def go():
        conv = await s.create_conversation({"k": "v"})
        await s.add_items(conv.id, [ConversationItem(
            type=hostile, role="user", content={"content": "hi"})])
        inserts = [x for x in fake.sql_log
                   if x.startswith("INSERT INTO conversation_items")]
        # the client value is a quoted literal; only the seq column splices
        assert "'evil.NEXTVAL'" in inserts[0]
        assert inserts[0].rstrip().endswith("smg_item_seq.NEXTVAL)")
        # quote-splicing data survives the roundtrip as data
        tricky = "x', (SELECT 1), 'y"
        await s.add_items(conv.id, [ConversationItem(
            type="message", role=tricky, content={"content": "z"})])
        items = await s.list_items(conv.id)
        assert items[-1].role == tricky
        # _insert only honors the module-private wrapper, not plain strings
        sql = s._insert("conversation_items", {
            "id": "i", "conversation_id": "c", "item_type": "t",
            "created_at": 0.0, "seq": _RawSql("smg_item_seq.NEXTVAL"),
        })
        assert sql.rstrip().endswith("smg_item_seq.NEXTVAL)")

    asyncio.run(go())


def test_oracle_migration_version_race_absorbed():
    """Two migrators race the smg_migrations INSERT: the loser hits
    ORA-00001 (PK on version) and must carry on, not surface the error."""

    class RacingOracle(FakeOracle):
        async def query(self, sql: str):
            if sql.startswith("INSERT INTO smg_migrations"):
                raise RuntimeError(
                    "ORA-00001: unique constraint (SMG_MIGRATIONS.PK) violated"
                )
            return await super().query(sql)

    fake = RacingOracle()
    s = OracleStorage(fake)

    async def go():
        await s._ensure()  # must not raise
        assert s._migrated
        # non-unique-violation errors still surface
        class BrokenOracle(FakeOracle):
            async def query(self, sql: str):
                if sql.startswith("INSERT INTO smg_migrations"):
                    raise RuntimeError("ORA-00942: table or view does not exist")
                return await super().query(sql)

        s2 = OracleStorage(BrokenOracle())
        with pytest.raises(RuntimeError, match="ORA-00942"):
            await s2._ensure()

    asyncio.run(go())


def test_oracle_schema_remapping():
    """Point the backend at an EXISTING physical schema: renamed tables and
    columns, an extra column, and a skipped one (schema.rs semantics)."""
    schema = SchemaConfig.from_json("""
    {
      "conversations": {
        "table": "CHAT_SESSIONS",
        "columns": {"id": "SESSION_ID", "created_at": "STARTED_AT"},
        "extra_columns": {"REGION": "VARCHAR2(32)"},
        "skip_columns": ["metadata"]
      },
      "conversation_items": {"table": "CHAT_TURNS",
                             "columns": {"item_type": "KIND"}}
    }
    """)
    fake = FakeOracle()
    s = OracleStorage(fake, schema=schema)

    async def go():
        conv = await s.create_conversation({"dropped": True})
        got = await s.get_conversation(conv.id)
        assert got is not None and got.metadata == {}  # metadata skipped
        await s.add_items(conv.id, [ConversationItem(
            type="message", role="user", content={"content": "hi"})])
        items = await s.list_items(conv.id)
        assert items[0].type == "message" and items[0].role == "user"
        # physical schema assertions
        ddl = "\n".join(x for x in fake.sql_log if x.startswith("CREATE TABLE"))
        assert "CHAT_SESSIONS" in ddl and "SESSION_ID" in ddl
        assert "REGION VARCHAR2(32)" in ddl
        assert "metadata" not in ddl.split("CHAT_SESSIONS")[1].split(")")[0]
        assert "CHAT_TURNS" in ddl and "KIND" in ddl
        inserts = [x for x in fake.sql_log if x.startswith("INSERT INTO CHAT_SESSIONS")]
        assert inserts and "SESSION_ID" in inserts[0]
        assert "metadata" not in inserts[0]

    asyncio.run(go())


def test_make_storage_oracle_scheme_needs_driver():
    from smg_tpu.storage import make_storage

    with pytest.raises(RuntimeError, match="oracledb"):
        make_storage("oracle://user:pw@dbhost:1521/XEPDB1")
