"""Reasoning + tool-call parser behavior tests, including chunked streaming
(reference: per-parser test suites under crates/{reasoning,tool}_parser)."""

import json

import pytest

from smg_tpu.parsers import get_reasoning_parser, get_tool_parser
from smg_tpu.parsers.partial_json import complete_json, parse_partial


def stream_chunks(parser, text, n=3):
    """Feed text in n-char chunks; collect output."""
    content = reasoning = ""
    for i in range(0, len(text), n):
        d = parser.feed(text[i : i + n])
        content += d.content
        reasoning += d.reasoning
    d = parser.flush()
    return content + d.content, reasoning + d.reasoning


# ---- reasoning ----

def test_reasoning_basic_split():
    p = get_reasoning_parser("qwen3")
    c, r = p.parse_full("<think>step by step</think>the answer is 4")
    assert r == "step by step"
    assert c == "the answer is 4"


def test_reasoning_initial_in_reasoning():
    p = get_reasoning_parser("deepseek-r1")
    c, r = p.parse_full("I reason here</think>final answer")
    assert r == "I reason here"
    assert c == "final answer"


def test_reasoning_streaming_split_across_chunks():
    for chunk in (1, 2, 3, 7):
        p = get_reasoning_parser("qwen3")
        c, r = stream_chunks(p, "<think>abc def</think>ghi", n=chunk)
        assert r == "abc def", f"chunk={chunk}"
        assert c == "ghi", f"chunk={chunk}"


def test_reasoning_no_tags_passthrough_family():
    p = get_reasoning_parser("qwen3")
    c, r = p.parse_full("plain text, no thinking")
    assert c == "plain text, no thinking" and r == ""


def test_reasoning_kimi_unicode_tags():
    p = get_reasoning_parser("kimi-k1.5")
    c, r = p.parse_full("◁think▷deep◁/think▷out")
    assert r == "deep" and c == "out"


def test_reasoning_unknown_model_passthrough():
    p = get_reasoning_parser("some-unknown-model")
    c, r = p.parse_full("<think>x</think>y")
    assert c == "<think>x</think>y" and r == ""


# ---- partial json ----

def test_complete_json_closes_scopes():
    assert json.loads(complete_json('{"a": [1, 2')) == {"a": [1, 2]}
    assert json.loads(complete_json('{"a": "uncl')) == {"a": "uncl"}
    assert complete_json('}{') is None


def test_parse_partial_trailing_key():
    assert parse_partial('{"name": "f", "arguments": {"x":') == {"name": "f"} or \
        parse_partial('{"name": "f", "arguments": {"x":') == {"name": "f", "arguments": {}}


# ---- tool calls ----

def tool_stream(parser, text, n=4):
    normal = ""
    calls = []
    for i in range(0, len(text), n):
        d = parser.feed(text[i : i + n])
        normal += d.normal_text
        calls.extend(d.calls)
    d = parser.flush()
    return normal + d.normal_text, calls + d.calls


def test_json_tool_parser():
    p = get_tool_parser("json")
    text = 'Sure thing {"name": "get_weather", "arguments": {"city": "Paris"}} done'
    normal, calls = p.parse_full(text)
    assert len(calls) == 1
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Paris"}
    assert "Sure thing" in normal and "done" in normal


def test_json_tool_array():
    p = get_tool_parser("json")
    _, calls = p.parse_full('[{"name": "a", "arguments": {}}, {"name": "b", "arguments": {"x": 1}}]')
    assert [c.name for c in calls] == ["a", "b"]
    assert calls[1].index == 1


def test_qwen_tool_parser_streaming():
    p = get_tool_parser("qwen2.5-72b")
    text = 'before <tool_call>\n{"name": "search", "arguments": {"q": "jax"}}\n</tool_call> after'
    for n in (3, 5, 100):
        p = get_tool_parser("qwen")
        normal, calls = tool_stream(p, text, n=n)
        assert len(calls) == 1, f"chunk={n}"
        assert calls[0].name == "search"
        assert json.loads(calls[0].arguments) == {"q": "jax"}
        assert "before" in normal and "after" in normal
        assert "<tool_call>" not in normal


def test_mistral_tool_parser():
    p = get_tool_parser("mistral-large")
    _, calls = p.parse_full('[TOOL_CALLS] [{"name": "f", "arguments": {"a": 1}}]')
    assert calls[0].name == "f"
    assert json.loads(calls[0].arguments) == {"a": 1}


def test_llama3_python_tag():
    p = get_tool_parser("llama-3.1-8b-instruct")
    _, calls = p.parse_full('<|python_tag|>{"name": "calc", "parameters": {"expr": "2+2"}}')
    assert calls[0].name == "calc"
    assert json.loads(calls[0].arguments) == {"expr": "2+2"}


def test_deepseek_tool_parser():
    p = get_tool_parser("deepseek-v3")
    text = (
        "<｜tool▁calls▁begin｜><｜tool▁call▁begin｜>function<｜tool▁sep｜>get_time\n"
        '```json\n{"tz": "UTC"}\n```<｜tool▁call▁end｜><｜tool▁calls▁end｜>'
    )
    _, calls = p.parse_full(text)
    assert calls[0].name == "get_time"
    assert json.loads(calls[0].arguments) == {"tz": "UTC"}


def test_kimi_k2_tool_parser():
    p = get_tool_parser("kimi-k2")
    text = (
        "<|tool_calls_section_begin|><|tool_call_begin|>functions.ls:0"
        '<|tool_call_argument_begin|>{"path": "/tmp"}<|tool_call_end|>'
        "<|tool_calls_section_end|>"
    )
    _, calls = p.parse_full(text)
    assert calls[0].name == "ls"
    assert json.loads(calls[0].arguments) == {"path": "/tmp"}


def test_glm4_moe_tool_parser():
    p = get_tool_parser("glm-4.5")
    text = (
        "<tool_call>get_weather\n"
        "<arg_key>city</arg_key>\n<arg_value>\"Beijing\"</arg_value>\n"
        "<arg_key>days</arg_key>\n<arg_value>3</arg_value>\n"
        "</tool_call>"
    )
    _, calls = p.parse_full(text)
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Beijing", "days": 3}


def test_pythonic_tool_parser():
    p = get_tool_parser("llama-4-scout")
    _, calls = p.parse_full('[get_weather(city="Paris", days=2), search(q="news")]')
    assert [c.name for c in calls] == ["get_weather", "search"]
    assert json.loads(calls[0].arguments) == {"city": "Paris", "days": 2}


def test_plain_text_not_mistaken_for_calls():
    for model in ("json", "qwen", "mistral", "llama"):
        p = get_tool_parser(model)
        normal, calls = p.parse_full("just plain prose with no tools at all")
        assert calls == []
        assert "plain prose" in normal


def test_json_like_text_without_name_is_text():
    p = get_tool_parser("json")
    normal, calls = p.parse_full('the object {"key": "value"} is not a call')
    assert calls == []
    assert '{"key": "value"}' in normal


def test_minimax_m2_parser():
    p = get_tool_parser("minimax-m2")
    text = ('before <minimax:tool_call><invoke name="get_weather">'
            '<parameter name="city">"Paris"</parameter>'
            '<parameter name="days">3</parameter>'
            '</invoke></minimax:tool_call> after')
    normal, calls = p.parse_full(text)
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Paris", "days": 3}
    assert "before" in normal and "after" in normal


def test_cohere_parser():
    p = get_tool_parser("command-a-03-2025")
    text = ('<|START_RESPONSE|>On it.<|END_RESPONSE|>\n<|START_ACTION|>\n'
            '[{"tool_name": "search", "parameters": {"q": "rust"}},\n'
            ' {"tool_name": "get_weather", "parameters": {"city": "Paris"}}]\n'
            '<|END_ACTION|>')
    normal, calls = p.parse_full(text)
    assert [c.name for c in calls] == ["search", "get_weather"]
    assert json.loads(calls[0].arguments) == {"q": "rust"}


def test_sarashina_parser():
    p = get_tool_parser("sarashina2-70b")
    for text in (
        "<|tool_calls|>[{'name': 'get_weather', 'arguments': {'city': 'Tokyo'}}]",
        "[{'name': 'get_weather', 'arguments': {'city': 'Tokyo'}}]",
    ):
        _, calls = p.parse_full(text)
        assert len(calls) == 1, text
        assert calls[0].name == "get_weather"
        assert json.loads(calls[0].arguments) == {"city": "Tokyo"}
    # plain list text is not a call
    normal, calls = p.parse_full("[1, 2, 3] is a list")
    assert calls == []


# ---- new dialects: deepseek31, dsml, qwen_xml, inkling, harmony ----


def stream_tool_chunks(parser, text, n=3):
    normal = ""
    calls = []
    for i in range(0, len(text), n):
        d = parser.feed(text[i : i + n])
        normal += d.normal_text
        calls += d.calls
    d = parser.flush()
    return normal + d.normal_text, calls + d.calls


DS31 = ("I'll check the weather."
        "<｜tool▁calls▁begin｜><｜tool▁call▁begin｜>get_weather<｜tool▁sep｜>"
        '{"city": "Paris"}<｜tool▁call▁end｜>'
        "<｜tool▁call▁begin｜>search<｜tool▁sep｜>"
        '{"q": "tpu"}<｜tool▁call▁end｜><｜tool▁calls▁end｜>'
        "<｜end▁of▁sentence｜>")


def test_deepseek31_parser():
    p = get_tool_parser("deepseek-v3.1")
    normal, calls = p.parse_full(DS31)
    assert normal == "I'll check the weather."
    assert [c.name for c in calls] == ["get_weather", "search"]
    assert json.loads(calls[0].arguments) == {"city": "Paris"}
    assert json.loads(calls[1].arguments) == {"q": "tpu"}


def test_deepseek31_streaming_chunked():
    for n in (1, 3, 7, 11):
        p = get_tool_parser("deepseek31")
        normal, calls = stream_tool_chunks(p, DS31, n=n)
        assert normal == "I'll check the weather.", (n, normal)
        assert [c.name for c in calls] == ["get_weather", "search"], n


def test_deepseek31_non_object_args_wrap():
    p = get_tool_parser("deepseek31")
    text = ("<｜tool▁calls▁begin｜><｜tool▁call▁begin｜>f<｜tool▁sep｜>"
            "[1, 2]<｜tool▁call▁end｜><｜tool▁calls▁end｜>")
    _, calls = p.parse_full(text)
    assert json.loads(calls[0].arguments) == {"value": [1, 2]}


DSML = ('Let me call a tool. <｜DSML｜invoke name="get_weather">'
        '<｜DSML｜parameter name="city" string="true">Paris</｜DSML｜parameter>'
        '<｜DSML｜parameter name="days" string="false">3</｜DSML｜parameter>'
        "</｜DSML｜invoke> done")


def test_deepseek_dsml_parser():
    p = get_tool_parser("deepseek-dsml")
    normal, calls = p.parse_full(DSML)
    assert normal == "Let me call a tool.  done"
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Paris", "days": 3}


def test_deepseek_dsml_json_body_and_streaming():
    text = ('<｜DSML｜invoke name="f">{"x": 1}</｜DSML｜invoke>')
    for n in (1, 4, 9):
        p = get_tool_parser("deepseek_dsml")
        normal, calls = stream_tool_chunks(p, text, n=n)
        assert normal == "", n
        assert json.loads(calls[0].arguments) == {"x": 1}, n


QWEN_XML = ("Sure.<tool_call>\n<function=get_weather>\n"
            "<parameter=city>\nSan Francisco\n</parameter>\n"
            "<parameter=days>\n3\n</parameter>\n"
            "<parameter=note>\nTom &amp; Jerry &lt;3\n</parameter>\n"
            "</function>\n</tool_call>")


def test_qwen_xml_parser():
    p = get_tool_parser("qwen3-coder-480b")
    assert p.name == "qwen_xml"
    normal, calls = p.parse_full(QWEN_XML)
    assert normal == "Sure."
    assert calls[0].name == "get_weather"
    args = json.loads(calls[0].arguments)
    assert args["city"] == "San Francisco"
    assert args["days"] == 3  # JSON literal coerced
    assert args["note"] == "Tom & Jerry <3"  # entities unescaped


def test_qwen_xml_streaming_chunked():
    for n in (1, 5, 13):
        p = get_tool_parser("qwen_xml")
        normal, calls = stream_tool_chunks(p, QWEN_XML, n=n)
        assert normal == "Sure.", n
        assert len(calls) == 1 and calls[0].name == "get_weather", n


INKLING = ("<|content_text|>Checking."
           '<|content_invoke_tool_json|>{"name": "get_weather", '
           '"arguments": {"city": "Paris"}}<|end_message|>'
           "<|content_text|>Done.<|content_model_end_sampling|>")


def test_inkling_parser():
    p = get_tool_parser("inkling-1")
    normal, calls = p.parse_full(INKLING)
    assert normal == "Checking.Done."
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Paris"}


def test_inkling_text_mode_discarded_and_streaming():
    text = ("A<|content_invoke_tool_text|>call tool here<|end_message|>B"
            '<|content_invoke_tool_json|>{"name": "f", "arguments": {}}'
            "<|end_message|>C")
    for n in (1, 4, 10):
        p = get_tool_parser("inkling")
        normal, calls = stream_tool_chunks(p, text, n=n)
        assert normal == "ABC", (n, normal)
        assert [c.name for c in calls] == ["f"], n


HARMONY = ("<|channel|>analysis<|message|>Need the weather first.<|end|>"
           "<|start|>assistant<|channel|>commentary to=functions.get_weather "
           '<|constrain|>json<|message|>{"city": "Paris"}<|call|>'
           "<|start|>assistant<|channel|>final<|message|>It is sunny.<|return|>")


def test_harmony_reasoning_and_tools_full():
    rp = get_reasoning_parser("gpt-oss-120b")
    content, reasoning = rp.parse_full(HARMONY)
    assert reasoning == "Need the weather first."
    tp = get_tool_parser("gpt-oss-120b")
    normal, calls = tp.parse_full(content)
    assert normal == "It is sunny."
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Paris"}


def test_harmony_streaming_pipeline_chunked():
    for n in (1, 3, 8, 17):
        rp = get_reasoning_parser("harmony")
        tp = get_tool_parser("harmony")
        reasoning = normal = ""
        calls = []
        for i in range(0, len(HARMONY), n):
            d = rp.feed(HARMONY[i : i + n])
            reasoning += d.reasoning
            if d.content:
                td = tp.feed(d.content)
                normal += td.normal_text
                calls += td.calls
        d = rp.flush()
        reasoning += d.reasoning
        td = tp.feed(d.content) if d.content else None
        if td:
            normal += td.normal_text
            calls += td.calls
        td = tp.flush()
        normal += td.normal_text
        calls += td.calls
        assert reasoning == "Need the weather first.", n
        assert normal == "It is sunny.", n
        assert [c.name for c in calls] == ["get_weather"], n
        assert json.loads(calls[0].arguments) == {"city": "Paris"}, n


def test_harmony_tool_on_analysis_channel():
    """Recipient check wins over channel (reference parser.rs:124-129)."""
    text = ("<|channel|>analysis to=functions.search <|constrain|>json"
            '<|message|>{"q": "x"}<|call|>')
    tp = get_tool_parser("harmony")
    normal, calls = tp.parse_full(text)
    assert calls and calls[0].name == "search"
    # reasoning parser must ALSO route it as a tool frame, not reasoning
    rp = get_reasoning_parser("harmony")
    content, reasoning = rp.parse_full(text)
    assert reasoning == ""
    assert "functions.search" in content


def test_parser_matrix_count():
    """The dialect matrix matches the reference's 19-parser surface."""
    from smg_tpu.parsers.tools import _PARSERS

    names = set(_PARSERS) | {"harmony"}
    assert len(names) >= 18, sorted(names)


def test_harmony_recipient_without_trailing_space():
    """Recipient jammed against the next control token still parses
    (gpt-oss emits both spacings)."""
    text = ('<|channel|>commentary to=functions.search<|constrain|>json'
            '<|message|>{"q": "x"}<|call|>')
    tp = get_tool_parser("harmony")
    _, calls = tp.parse_full(text)
    assert calls and calls[0].name == "search"


def test_qwen_xml_numeric_entities():
    text = ("<tool_call>\n<function=f>\n<parameter=s>\nit&#39;s &#x26; ok\n"
            "</parameter>\n</function>\n</tool_call>")
    p = get_tool_parser("qwen_xml")
    _, calls = p.parse_full(text)
    assert json.loads(calls[0].arguments)["s"] == "it's & ok"
