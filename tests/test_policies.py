"""Policy unit tests (reference: per-policy tests in model_gateway/src/policies/)."""

from dataclasses import dataclass, field

import pytest

from smg_tpu.policies import RequestContext, get_policy
from smg_tpu.protocols.events import BlockStored, KvEventBatch


@dataclass
class FakeWorker:
    worker_id: str
    model_id: str = "m"
    load: int = 0
    healthy: bool = True

    def is_available(self) -> bool:
        return self.healthy


def workers(n=4, **kw):
    return [FakeWorker(worker_id=f"w{i}", **kw) for i in range(n)]


def ctx(**kw):
    return RequestContext(**kw)


def test_round_robin_cycles():
    p = get_policy("round_robin")
    ws = workers(3)
    picks = [p.select_worker(ws, ctx()).worker_id for _ in range(6)]
    assert picks == ["w0", "w1", "w2", "w0", "w1", "w2"]


def test_round_robin_skips_unhealthy():
    p = get_policy("round_robin")
    ws = workers(3)
    ws[1].healthy = False
    picks = {p.select_worker(ws, ctx()).worker_id for _ in range(4)}
    assert "w1" not in picks


def test_no_workers_returns_none():
    for name in ("round_robin", "random", "least_load", "power_of_two", "cache_aware"):
        assert get_policy(name).select_worker([], ctx()) is None


def test_least_load():
    p = get_policy("least_load", seed=0)
    ws = workers(3)
    ws[0].load = 5
    ws[1].load = 1
    ws[2].load = 3
    assert p.select_worker(ws, ctx()).worker_id == "w1"


def test_power_of_two_prefers_lower_load():
    p = get_policy("power_of_two", seed=0)
    ws = workers(2)
    ws[0].load = 10
    picks = [p.select_worker(ws, ctx()).worker_id for _ in range(10)]
    assert all(x == "w1" for x in picks)


def test_manual_sticky():
    p = get_policy("manual", seed=0)
    ws = workers(4)
    a = p.select_worker(ws, ctx(routing_key="user-1")).worker_id
    for _ in range(5):
        assert p.select_worker(ws, ctx(routing_key="user-1")).worker_id == a
    p.on_worker_removed(a)
    ws = [w for w in ws if w.worker_id != a]
    b = p.select_worker(ws, ctx(routing_key="user-1")).worker_id
    assert b != a


def test_consistent_hashing_stable_and_minimal_disruption():
    p = get_policy("consistent_hashing")
    ws = workers(4)
    keys = [f"key-{i}" for i in range(50)]
    before = {k: p.select_worker(ws, ctx(routing_key=k)).worker_id for k in keys}
    after_same = {k: p.select_worker(ws, ctx(routing_key=k)).worker_id for k in keys}
    assert before == after_same
    ws2 = ws[:3]  # w3 removed
    after = {k: p.select_worker(ws2, ctx(routing_key=k)).worker_id for k in keys}
    moved = sum(1 for k in keys if before[k] != after[k] and before[k] != "w3")
    assert moved == 0  # only keys on the removed worker move


def test_prefix_hash_same_prefix_same_worker():
    p = get_policy("prefix_hash", prefix_tokens=4)
    ws = workers(4)
    a = p.select_worker(ws, ctx(token_ids=[1, 2, 3, 4, 99]))
    b = p.select_worker(ws, ctx(token_ids=[1, 2, 3, 4, 42, 77]))
    assert a.worker_id == b.worker_id


def test_bucket_separates_length_bands():
    p = get_policy("bucket", boundaries=(10,))
    ws = workers(4)
    short = p.select_worker(ws, ctx(token_ids=list(range(5))))
    long = p.select_worker(ws, ctx(token_ids=list(range(50))))
    assert short.worker_id != long.worker_id


def test_cache_aware_approx_affinity():
    p = get_policy("cache_aware", mode="approx_token", match_threshold=0.3, seed=0)
    ws = workers(4)
    prefix = list(range(100))
    first = p.select_worker(ws, ctx(token_ids=prefix))
    # same long prefix + small suffix: must stick to the same worker
    for i in range(5):
        again = p.select_worker(ws, ctx(token_ids=prefix + [200 + i]))
        assert again.worker_id == first.worker_id


def test_cache_aware_imbalance_falls_back_to_shortest_queue():
    p = get_policy("cache_aware", mode="approx_token", imbalance_abs=4, imbalance_rel=1.2, seed=0)
    ws = workers(2)
    prefix = list(range(64))
    first = p.select_worker(ws, ctx(token_ids=prefix))
    first.load = 50  # heavy imbalance toward the cached worker
    other = [w for w in ws if w is not first][0]
    pick = p.select_worker(ws, ctx(token_ids=prefix))
    assert pick.worker_id == other.worker_id


def test_cache_aware_event_mode():
    p = get_policy("cache_aware", mode="event", match_threshold=0.4, page_size=4, seed=0)
    ws = workers(3)
    tokens = list(range(16))
    # simulate w2 holding the first 3 pages of this prompt
    from smg_tpu.kv_index.positional import chain_hash

    hashes, parent = [], 0
    for i in range(3):
        parent = chain_hash(parent, tuple(tokens[i * 4 : (i + 1) * 4]))
        hashes.append(parent)
    p.apply_kv_events(
        "w2",
        KvEventBatch(
            sequence_number=1,
            events=[BlockStored(block_hashes=hashes, token_ids=tokens[:12], block_size=4)],
        ),
    )
    assert p.select_worker(ws, ctx(token_ids=tokens)).worker_id == "w2"


def test_radix_tree_prefix_match():
    from smg_tpu.kv_index import RadixTree

    t = RadixTree()
    t.insert("hello world", "w0")
    t.insert("hello there", "w1")
    m = t.prefix_match("hello world!")
    assert m["w0"] == len("hello world")
    assert m["w1"] == len("hello ")
    t.remove_worker("w0")
    m2 = t.prefix_match("hello world!")
    assert "w0" not in m2


def test_native_radix_parity_with_python():
    """Native C++ tree and Python tree agree on random workloads
    (skipped when no toolchain built the native library)."""
    import random

    from smg_tpu.kv_index import RadixTree
    from smg_tpu.kv_index.native import native_available, NativeRadixTree

    if not native_available():
        pytest.skip("native radix library not built")
    rng = random.Random(0)
    py = RadixTree()
    nat = NativeRadixTree()
    seqs = []
    for i in range(200):
        base = seqs[rng.randrange(len(seqs))][: rng.randrange(1, 20)] if seqs and rng.random() < 0.5 else []
        seq = base + [rng.randrange(64) for _ in range(rng.randrange(1, 30))]
        seqs.append(seq)
        w = f"w{rng.randrange(4)}"
        py.insert(seq, w)
        nat.insert(seq, w)
    for _ in range(100):
        probe = seqs[rng.randrange(len(seqs))] + [rng.randrange(64)]
        assert py.prefix_match(probe) == nat.prefix_match(probe)
    py.remove_worker("w1")
    nat.remove_worker("w1")
    for _ in range(50):
        probe = seqs[rng.randrange(len(seqs))]
        assert py.prefix_match(probe) == nat.prefix_match(probe)


# ---- routing decision records (gateway/route_observability.py consumes) ----


ALL_POLICY_NAMES = (
    "round_robin", "random", "least_load", "power_of_two", "passthrough",
    "manual", "consistent_hashing", "prefix_hash", "bucket", "cache_aware",
)


def test_every_policy_emits_schema_stable_decision():
    """select() returns (worker, RouteDecision) for EVERY registered policy,
    and to_dict() holds exactly the pinned schema keys (dashboards pin
    against DECISION_KEYS; extend, never rename)."""
    from smg_tpu.policies import DECISION_KEYS, RouteDecision

    for name in ALL_POLICY_NAMES:
        p = get_policy(name)
        ws = workers(4)
        w, d = p.select(
            ws, ctx(token_ids=list(range(32)), routing_key="k", request_id="r1")
        )
        assert w is not None, name
        assert isinstance(d, RouteDecision), name
        assert d.policy == name
        assert d.chosen == w.worker_id, name
        assert d.outcome not in ("", "none"), name
        assert d.decision_us > 0, name
        assert d.request_id == "r1"
        # candidate snapshot covers the full pool
        assert {c[0] for c in d.candidates} == {x.worker_id for x in ws}, name
        assert set(d.to_dict()) == set(DECISION_KEYS), name


@pytest.mark.parametrize("name", ALL_POLICY_NAMES)
def test_decision_no_worker_outcome(name):
    """EVERY policy labels an empty-pool selection 'no_worker' — dashboards
    alert on that outcome, so a policy stamping its own name before the
    availability check (the random/passthrough regression) hides outages."""
    p = get_policy(name)
    ws = workers(2)
    for w in ws:
        w.healthy = False
    w, d = p.select(ws, ctx(token_ids=list(range(8)), routing_key="k"))
    assert w is None, name
    assert d.chosen is None, name
    assert d.outcome == "no_worker", name


def test_cache_oblivious_policy_predicts_zero_reuse():
    """round_robin has no cache model: its implicit prediction is 0 cached
    tokens, so reconciliation measures what cache-oblivious routing leaves
    on the table."""
    p = get_policy("round_robin")
    w, d = p.select(workers(2), ctx(token_ids=list(range(16))))
    assert d.predicted_match_tokens == 0
    # text-only requests have no token-space prediction to reconcile
    _, d2 = p.select(workers(2), ctx(text="hello"))
    assert d2.predicted_match_tokens is None


def test_cache_aware_decision_prefix_hit_fields():
    p = get_policy("cache_aware", mode="approx_token", match_threshold=0.3, seed=0)
    ws = workers(4)
    prefix = list(range(100))
    first, d0 = p.select(ws, ctx(token_ids=prefix))
    assert d0.mode == "approx_token"
    assert d0.outcome in ("no_match", "below_threshold")  # cold tree
    assert d0.predicted_match_tokens in (0, None) or d0.predicted_match_tokens >= 0
    again, d = p.select(ws, ctx(token_ids=prefix + [500]))
    assert again.worker_id == first.worker_id
    assert d.outcome == "prefix_hit"
    assert d.prefix_matches[first.worker_id] == 100
    assert d.predicted_match_tokens == 100
    assert 0.9 < d.predicted_match_fraction <= 1.0
    assert d.match_threshold == 0.3
    assert d.tie_break in ("unique_best",) or d.tie_break.startswith("load_then_id")


def test_cache_aware_decision_imbalance_override():
    p = get_policy(
        "cache_aware", mode="approx_token", imbalance_abs=4, imbalance_rel=1.2, seed=0
    )
    ws = workers(2)
    prefix = list(range(64))
    first, _ = p.select(ws, ctx(token_ids=prefix))
    first.load = 50
    pick, d = p.select(ws, ctx(token_ids=prefix))
    assert pick.worker_id != first.worker_id
    assert d.imbalanced is True
    assert d.outcome == "imbalance_override"
    # the override skips the index walk: no prediction exists, so the
    # decision must NOT reconcile (an implicit 0 would corrupt the
    # per-worker index-staleness EMA with decisions the index never made)
    assert d.predicted_match_tokens is None


def test_cache_aware_decision_below_threshold():
    p = get_policy("cache_aware", mode="approx_token", match_threshold=0.9, seed=0)
    ws = workers(2)
    p.select(ws, ctx(token_ids=list(range(100))))
    # 32/132 ≈ 24% overlap < 90% threshold: match exists but is rejected
    _, d = p.select(ws, ctx(token_ids=list(range(32)) + list(range(900, 1000))))
    assert d.outcome == "below_threshold"
    assert d.predicted_match_tokens is not None


def test_cache_aware_approx_string_scales_prediction_to_tokens():
    p = get_policy("cache_aware", mode="approx_string", match_threshold=0.1, seed=0)
    ws = workers(2)
    toks = list(range(40))
    first, _ = p.select(ws, ctx(text="abcd" * 25, token_ids=toks))
    _, d = p.select(ws, ctx(text="abcd" * 25, token_ids=toks))
    if d.outcome == "prefix_hit":
        # char-space match rescaled through the tokenized length
        assert d.predicted_match_tokens == len(toks)


def test_decision_sink_receives_records_and_failures_never_break_routing():
    from smg_tpu.policies import RouteDecision

    class Sink:
        def __init__(self):
            self.records = []

        def record(self, d):
            self.records.append(d)

    p = get_policy("least_load", seed=0)
    sink = Sink()
    p._decision_sink = sink
    w, d = p.select(workers(3), ctx())
    assert sink.records == [d]

    class BrokenSink:
        def record(self, d):
            raise RuntimeError("observability must never fail routing")

    p._decision_sink = BrokenSink()
    w2, _ = p.select(workers(3), ctx())
    assert w2 is not None
