"""On-device megastep decode: the scan-fused K-step loop with device-side
stop detection must be BYTE-IDENTICAL to K=1 at any temperature.

The invariant chain under test:

- every megastep column folds the exact sampling key the single-step path
  would have folded at that global step (in-loop folds);
- the device done mask (EOS/stop-token id sets + per-lane length limits)
  early-exits the loop at the first finishing lane;
- the host trims acceptance at the earliest finish column (the K=1
  batch-recomposition point) and rewinds the unused key folds;
- the overlap pipeline's chained lookahead frames and the quarantine
  recovery path rewind a whole discarded horizon's folds (LIFO).

Any slip in any of these flips a temp-0.8 stream, so the K-sweep parity
tests are the gate.  The adaptive horizon controller and the one-trace-per
-batch-bucket compile guarantee ride along."""

import pytest

from smg_tpu.engine.config import SchedulerConfig
from smg_tpu.faults import FAULTS
from smg_tpu.protocols.sampling import SamplingParams

from tests.test_overlap import greedy, make_engine, run_streams


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    FAULTS.clear()


def assert_stream_parity(got, base, what=""):
    """Byte-identical token streams, text, finish reasons, and matched
    stops; logprobs within 1e-3.  K=1 and K>1 run DIFFERENT compiled loop
    widths, and XLA's reduction order inside the sampler's logsumexp is not
    bit-stable across program shapes — tokens are exact (argmax), the
    reported logprob can move a few 1e-5."""
    assert set(got) == set(base)
    for rid in base:
        bt, btx, br, bm, bl = base[rid]
        gt, gtx, gr, gm, gl = got[rid]
        assert (gt, gtx, gr, gm) == (bt, btx, br, bm), (
            f"{what}: stream for {rid!r} diverged:\n{got[rid]}\nvs\n{base[rid]}"
        )
        assert len(gl) == len(bl) and all(
            abs(a - b) < 1e-3 for a, b in zip(gl, bl)
        ), f"{what}: logprobs for {rid!r} drifted past tolerance"


MIXED_JOBS = [
    # greedy, sampled, and penalty lanes; staggered lengths so finishes land
    # at many different columns inside a K>1 horizon (the penalty lane also
    # pins the on-device count updates across trims and discarded frames)
    ("g0", list(range(5, 25)), greedy(13)),
    ("s0", list(range(30, 55)),
     SamplingParams(temperature=0.8, top_k=40, top_p=0.95,
                    max_new_tokens=9, ignore_eos=True)),
    ("s1", list(range(60, 75)),
     SamplingParams(temperature=0.8, min_p=0.02, max_new_tokens=5,
                    ignore_eos=True)),
    ("p0", list(range(80, 100)),
     SamplingParams(temperature=0.8, frequency_penalty=0.4,
                    max_new_tokens=11, ignore_eos=True)),
]


@pytest.fixture(scope="module")
def k1_baseline():
    """The K=1 stream set every megastep configuration must reproduce."""
    return run_streams(make_engine(True), MIXED_JOBS)


# tier-1 wall-clock: K=4 (both schedules) is the in-band gate; the K∈{2,8}
# variants ride the slow lane with the exhaustive sweep (ROADMAP practical
# note — the full suite must fit the 870s harness timeout)
@pytest.mark.parametrize("horizon", [
    pytest.param(2, marks=pytest.mark.slow), 4,
    pytest.param(8, marks=pytest.mark.slow),
])
@pytest.mark.parametrize("overlap", [True, False])
def test_k_sweep_byte_identical_to_k1(horizon, overlap, k1_baseline):
    got = run_streams(make_engine(overlap, decode_horizon=horizon), MIXED_JOBS)
    assert_stream_parity(got, k1_baseline,
                         f"megastep K={horizon} overlap={overlap}")


def test_eos_and_stop_token_finish_inside_horizon():
    """Natural EOS and stop_token_ids finishing mid-horizon: the device done
    mask must end the horizon at that column and the stream must equal K=1
    (including finish_reason/matched_stop)."""
    probe = run_streams(
        make_engine(False), [("p", list(range(5, 15)), greedy(6))]
    )["p"][0]
    stop_tok = probe[3]
    jobs = [
        ("e0", list(range(5, 15)),
         SamplingParams(temperature=0.0, max_new_tokens=32)),  # natural EOS
        ("e1", list(range(5, 15)),
         SamplingParams(temperature=0.0, max_new_tokens=32, ignore_eos=True,
                        stop_token_ids=[stop_tok])),
    ]
    base = run_streams(make_engine(True), jobs)
    e8 = make_engine(True, decode_horizon=8)
    got = run_streams(e8, jobs)
    assert_stream_parity(got, base, "eos/stop-token inside horizon")
    assert got["e1"][2] == "stop" and got["e1"][3] == stop_tok
    # the finishes landed mid-horizon, so the device loop must have exited
    # early rather than computing the full K columns
    assert e8.scheduler.num_megastep_early_exits > 0


def test_max_tokens_finish_inside_horizon_wastes_nothing():
    """A length finish at max_new % K != 0 ends the horizon mid-frame.  In
    the synchronous schedule (no lookahead frames to discard) the device
    early exit must make the megastep completely waste-free: every computed
    column is an accepted column."""
    jobs = [(f"m{i}", list(range(5 + 20 * i, 25 + 20 * i)), greedy(9 + i))
            for i in range(3)]
    base = run_streams(make_engine(False), jobs)
    e8 = make_engine(False, decode_horizon=8)
    got = run_streams(e8, jobs)
    assert_stream_parity(got, base, "max-tokens inside horizon")
    assert e8.scheduler.num_megastep_early_exits > 0
    assert e8.scheduler.num_wasted_decode_tokens == 0
    for rid, (toks, _t, reason, _m, _l) in got.items():
        assert reason == "length" and len(toks) == 9 + int(rid[1])


def test_stop_string_forces_horizon_one():
    """Stop strings match at the ENGINE layer after detokenization — the
    device done mask cannot see them — so any lane carrying one forces K=1
    (the same conservative rule as the overlap sync-forcing paths), and the
    stream still equals the K=1 engine's."""
    probe = run_streams(
        make_engine(False), [("p", list(range(60, 90)), greedy(8))]
    )["p"][0]
    stop_word = f"w{probe[2]}"
    jobs = [
        ("r0", list(range(60, 90)),
         SamplingParams(temperature=0.0, max_new_tokens=16, ignore_eos=True,
                        stop=[stop_word])),
        ("r1", list(range(7, 31)), greedy(14)),
    ]
    base = run_streams(make_engine(True), jobs)
    e8 = make_engine(True, decode_horizon=8)
    got = run_streams(e8, jobs)
    assert_stream_parity(got, base, "stop-string forced K=1")
    assert got["r0"][2] == "stop" and not got["r0"][1].endswith(stop_word)

    # white-box (reusing the drained K=8 engine): a lane set containing a
    # stop-string request picks (1, 1)
    e8.submit(list(range(60, 90)),
              SamplingParams(temperature=0.0, max_new_tokens=16,
                             ignore_eos=True, stop=[stop_word]), rid="w")
    for _ in range(2):
        e8.step()
    active = e8.scheduler._decode_active()
    assert active and e8.scheduler._pick_horizon(active) == (1, 1)
    while e8.scheduler.has_work():
        e8.step()


def test_chunked_prefill_admission_mid_horizon_parity():
    """A multi-chunk prompt admits under the per-step budget while K=4
    megasteps are in flight: resumable (fold-free) chunks must leave the
    in-loop fold sequence untouched and the final sampling chunk must order
    its fold before the next horizon's — any slip flips the temp-0.8
    streams."""
    jobs = [
        ("long", list(range(5, 185)),
         SamplingParams(temperature=0.8, top_k=40, max_new_tokens=10,
                        ignore_eos=True)),
        ("c0", list(range(200, 240)),
         SamplingParams(temperature=0.8, max_new_tokens=12, ignore_eos=True)),
        ("c1", list(range(250, 275)), greedy(9)),
    ]
    base = run_streams(make_engine(True), jobs)
    for overlap in (True, False):
        got = run_streams(make_engine(overlap, decode_horizon=4), jobs)
        assert_stream_parity(got, base,
                             f"chunked admission (overlap={overlap})")


def test_quarantine_rewind_across_megastep():
    """A poison decode step at K=4 quarantines the newest lane; the retry
    must refold the SAME keys the K=1 engine's recovery folds, which only
    holds if drop_inflight rewinds the whole discarded horizon's folds
    (frame.folds, not 1).  Survivor streams are compared between the
    faulted K=4 and faulted K=1 runs at temp 0.8 — key-sensitive."""

    def run(horizon: int) -> dict:
        eng = make_engine(True, decode_horizon=horizon)
        jobs = [
            (f"q{i}", list(range(5 + 30 * i, 25 + 30 * i)),
             SamplingParams(temperature=0.8, top_k=50, max_new_tokens=8,
                            ignore_eos=True))
            for i in range(3)
        ]
        chunks: dict = {rid: [] for rid, _, _ in jobs}
        for rid, prompt, sp in jobs:
            eng.submit(prompt, sp, rid=rid,
                       on_output=lambda o, rid=rid: chunks[rid].append(o))
        eng.step()  # admit + prefill all three
        FAULTS.arm("engine.decode_step", mode="once")
        for _ in range(200):
            if all(v and v[-1].finished for v in chunks.values()):
                break
            eng.step()
        while eng.scheduler.has_work():
            eng.step()
        FAULTS.clear()
        assert eng.scheduler.num_quarantined == 1
        return {
            rid: ([t for o in v for t in o.new_token_ids],
                  v[-1].finish_reason)
            for rid, v in chunks.items()
        }

    k4, k1 = run(4), run(1)
    # newest admission (q2) is blamed in both
    assert k4["q2"][1] == "error" and k1["q2"][1] == "error"
    for rid in ("q0", "q1"):
        assert k4[rid] == k1[rid], f"survivor {rid} diverged across megastep"


def test_static_horizon_page_pressure_parity():
    """The page-headroom clamp applies to the STATIC path too: a fixed K=8
    under a tight page pool must not make _ensure_seq_capacity preempt a
    peer the K=1 schedule would never touch (a preemption refolds the
    victim's keys — temp-0.8 streams would diverge).  The pool here drains
    to ~zero as three lanes grow, so unclamped K=8 launches would demand
    pages the pool cannot give without eviction."""
    jobs = [
        (f"pp{i}", list(range(5 + 40 * i, 40 + 40 * i)),
         SamplingParams(temperature=0.8, top_k=50, max_new_tokens=40,
                        ignore_eos=True))
        for i in range(3)
    ]
    kw = dict(num_pages=16, max_batch=4, max_seq_len=128)
    base = run_streams(make_engine(True, **kw), jobs)
    got = run_streams(make_engine(True, decode_horizon=8, **kw), jobs)
    assert_stream_parity(got, base, "static K=8 under page pressure")


def test_steady_state_guard_clean_at_k8():
    """Steady-state megastep decode at K=8: 0 recompiles and no implicit
    transfers across guarded steps (the per-launch K scalar, positions, and
    the in-loop fold's step counter all ride explicit uploads)."""
    from smg_tpu.analysis.runtime_guards import steady_state_guard

    eng = make_engine(True, decode_horizon=8, max_seq_len=512, num_pages=256)
    done: dict = {}
    prompts = [[(7 * i + j) % 90 + 5 for j in range(16)] for i in range(2)]
    for i, p in enumerate(prompts):
        eng.submit(p, greedy(200), rid=f"r{i}",
                   on_output=lambda o, i=i: done.setdefault(i, []).append(o))
    for _ in range(6):  # warmup: prefill + pipeline priming + compiles
        eng.step()
    with steady_state_guard() as cc:
        for _ in range(8):
            eng.step()
    assert cc.count == 0
    while eng.scheduler.has_work():
        eng.step()
    lens = {i: sum(len(o.new_token_ids) for o in v) for i, v in done.items()}
    assert lens == {0: 200, 1: 200}


def test_one_trace_serves_every_k():
    """One megastep trace per batch bucket: the compiled loop width is the
    horizon cap and the per-launch K rides a device scalar, so an adaptive
    controller sweeping K must never add a decode_multi variant."""
    eng = make_engine(True, decode_horizon=2, adaptive_horizon=True,
                      decode_horizon_max=8)
    run_streams(eng, [("a", list(range(5, 25)), greedy(30))])
    traces = [k for k in eng.runner._compiled if k[0] == "decode_multi"]
    assert len(traces) == 1
    # force K variation: a waiting queue collapses K to 1, its drain
    # re-opens the cap — same trace throughout
    run_streams(eng, [
        ("b", list(range(5, 25)), greedy(25)),
        ("c", list(range(30, 55)), greedy(12)),
        ("d", list(range(60, 85)), greedy(6)),
    ])
    traces = {k for k in eng.runner._compiled if k[0] == "decode_multi"}
    # at most one more variant (batch bucket 4 vs 1), never one per K
    assert len(traces) <= 2
    assert all(k[3] == 8 for k in traces)  # compiled width == cap everywhere


def test_adaptive_horizon_controller_behaviors():
    eng = make_engine(True, decode_horizon=1, adaptive_horizon=True,
                      decode_horizon_max=8)
    sched = eng.scheduler
    eng.submit(list(range(5, 25)), greedy(64), rid="a")
    for _ in range(3):
        eng.step()
    active = sched._decode_active()
    assert active
    # empty queue, no finish history: controller opens up to the cap
    assert sched._pick_horizon(active) == (8, 8)
    # pending admission work forces K=1 (a K=1 schedule can admit between
    # any two columns — byte-parity), within the same wide trace
    eng.submit(list(range(30, 60)), greedy(8), rid="b")
    assert sched._pick_horizon(active) == (1, 8)
    while sched.has_work():
        eng.step()
    # short observed finish gaps shrink K
    eng2 = make_engine(True, decode_horizon=1, adaptive_horizon=True,
                       decode_horizon_max=8)
    run_streams(eng2, [
        (f"s{i}", list(range(5 + 20 * i, 25 + 20 * i)),
         SamplingParams(temperature=0.0, max_new_tokens=2, ignore_eos=True))
        for i in range(3)
    ])
    assert 0 < eng2.scheduler._finish_gap_ema <= 4
    eng2.submit(list(range(5, 25)), greedy(64), rid="z")
    for _ in range(2):
        eng2.step()
    act2 = eng2.scheduler._decode_active()
    assert act2 and eng2.scheduler._pick_horizon(act2)[0] < 8


def test_adaptive_parity_under_churn(k1_baseline):
    """The adaptive controller changes K frame to frame; accepted streams
    must not notice (K-invariance is the whole point of the trim rule)."""
    got = run_streams(
        make_engine(True, decode_horizon=1, adaptive_horizon=True,
                    decode_horizon_max=8),
        MIXED_JOBS,
    )
    assert_stream_parity(got, k1_baseline, "adaptive horizon churn")


def test_flight_ring_and_metrics_record_megastep():
    from prometheus_client import generate_latest

    eng = make_engine(True, decode_horizon=4)
    run_streams(eng, [
        ("f0", list(range(5, 25)), greedy(10)),
        ("f1", list(range(30, 50)),
         SamplingParams(temperature=0.0, max_new_tokens=6, ignore_eos=True)),
    ])
    ring = eng.dump_flight()["ring"]
    assert any(r["horizon"] == 4 for r in ring)
    assert any(r["early_exits"] for r in ring)  # a finish ended a horizon
    assert all("wasted_decode_tokens" in r for r in ring)
    text = generate_latest(eng.metrics.registry).decode()
    assert "smg_engine_decode_horizon 4.0" in text
    assert "smg_engine_megastep_early_exits_total" in text
    assert "smg_engine_wasted_decode_tokens_total" in text


def test_cli_horizon_flags_reach_scheduler_config():
    from smg_tpu.cli import build_parser
    from smg_tpu.config.validation import validate_cli_args

    args = build_parser().parse_args([
        "worker", "--model-preset", "tiny",
        "--decode-horizon", "4", "--adaptive-horizon", "on",
        "--decode-horizon-max", "16",
    ])
    assert not [i for i in validate_cli_args(args) if i.severity == "error"]
    sc = SchedulerConfig(
        decode_horizon=args.decode_horizon,
        adaptive_horizon=args.adaptive_horizon == "on",
        decode_horizon_max=args.decode_horizon_max,
    )
    assert (sc.decode_horizon, sc.adaptive_horizon, sc.horizon_cap) \
        == (4, True, 16)

    bad = build_parser().parse_args(
        ["worker", "--model-preset", "tiny", "--decode-horizon", "0"])
    assert [i for i in validate_cli_args(bad) if i.severity == "error"]
    bad2 = build_parser().parse_args([
        "worker", "--model-preset", "tiny",
        "--decode-horizon", "8", "--decode-horizon-max", "4",
    ])
    assert [i for i in validate_cli_args(bad2) if i.severity == "error"]


def test_launch_wires_horizon_flags():
    from smg_tpu.cli import build_parser
    from smg_tpu.gateway.launch import build_engine_from_args

    args = build_parser().parse_args([
        "worker", "--model-preset", "tiny", "--dtype", "float32",
        "--max-batch-size", "4", "--max-seq-len", "256",
        "--decode-horizon", "4", "--adaptive-horizon", "on",
        "--decode-horizon-max", "8",
    ])
    eng = build_engine_from_args(args)
    try:
        sc = eng.config.scheduler
        assert sc.decode_horizon == 4
        assert sc.adaptive_horizon is True
        assert sc.horizon_cap == 8
    finally:
        eng.stop()


@pytest.mark.slow
@pytest.mark.parametrize("horizon", [2, 4, 8])
def test_exhaustive_k_parity_sweep(horizon):
    """Randomized stress: mixed greedy/sampled/stop/penalty workloads, many
    staggered finish points, K vs K=1 AND overlap vs sync at each K."""
    import random

    rng = random.Random(1000 + horizon)
    jobs = []
    for i in range(6):
        prompt = [rng.randrange(5, 500) for _ in range(rng.randrange(8, 60))]
        if i % 3 == 0:
            sp = greedy(rng.randrange(3, 20))
        elif i % 3 == 1:
            sp = SamplingParams(temperature=0.8, top_k=50,
                                max_new_tokens=rng.randrange(3, 20),
                                ignore_eos=True)
        else:
            sp = SamplingParams(temperature=0.0,
                                max_new_tokens=rng.randrange(6, 24),
                                frequency_penalty=0.3, ignore_eos=True)
        jobs.append((f"x{i}", prompt, sp))
    base = run_streams(make_engine(True), jobs)
    assert_stream_parity(
        run_streams(make_engine(True, decode_horizon=horizon), jobs), base,
        f"exhaustive K={horizon} overlap")
    assert_stream_parity(
        run_streams(make_engine(False, decode_horizon=horizon), jobs), base,
        f"exhaustive K={horizon} sync")
