"""gRPC worker protocol e2e: engine behind the servicer on localhost, driven
through GrpcWorkerClient (reference: tier-2 mock-worker gRPC tests +
grpc_servicer proto tests, SURVEY.md §4)."""

import asyncio
import threading

import pytest

from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
from smg_tpu.engine.engine import Engine
from smg_tpu.gateway.worker_client import WorkerGenerateRequest
from smg_tpu.models.config import tiny_test_config
from smg_tpu.protocols.sampling import SamplingParams
from smg_tpu.rpc.client import GrpcWorkerClient
from smg_tpu.rpc.server import serve_worker_async


def make_engine() -> Engine:
    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False, dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
            prefill_token_buckets=(32, 64), decode_batch_buckets=(4,),
        ),
        dtype="float32",
        model_id="tiny-rpc",
    )
    from smg_tpu.tokenizer import MockTokenizer

    return Engine(cfg, tokenizer=MockTokenizer())


@pytest.fixture(scope="module")
def rpc():
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=120):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    engine = make_engine()
    engine.start()

    async def _setup():
        server = await serve_worker_async(engine, port=0, host="127.0.0.1")
        client = GrpcWorkerClient(f"127.0.0.1:{server._bound_port}")
        return server, client

    server, client = run(_setup())

    class H:
        pass

    h = H()
    h.run = run
    h.client = client
    h.engine = engine
    yield h
    run(client.close())
    run(server.stop(grace=None))
    loop.call_soon_threadsafe(loop.stop)
    engine.stop()


def test_model_info_and_health(rpc):
    info = rpc.run(rpc.client.get_model_info())
    assert info["model_id"] == "tiny-rpc"
    assert info["page_size"] == 16
    assert rpc.run(rpc.client.health()) is True


def test_generate_stream_over_grpc(rpc):
    async def go():
        chunks = []
        req = WorkerGenerateRequest(
            rid="rpc-1",
            input_ids=list(range(5, 25)),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=6, ignore_eos=True),
        )
        async for c in rpc.client.generate(req):
            chunks.append(c)
        return chunks

    chunks = rpc.run(go())
    assert chunks[-1].finished
    assert chunks[-1].finish_reason == "length"
    tokens = [t for c in chunks for t in c.token_ids]
    assert len(tokens) == 6
    assert chunks[-1].prompt_tokens == 20


def test_loads_over_grpc(rpc):
    loads = rpc.run(rpc.client.get_loads())
    assert loads["total_pages"] == 128
    assert loads["num_running"] == 0


def test_kv_events_over_grpc(rpc):
    async def go():
        batches = []
        got = asyncio.Event()

        def cb(batch):
            batches.append(batch)
            got.set()

        unsub = rpc.client.subscribe_kv_events(cb)
        # generate to produce BlockStored events
        req = WorkerGenerateRequest(
            rid="rpc-kv",
            input_ids=list(range(40, 80)),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=4, ignore_eos=True),
        )
        async for _ in rpc.client.generate(req):
            pass
        await asyncio.wait_for(got.wait(), timeout=10)
        unsub()
        return batches

    batches = rpc.run(go())
    assert batches
    stored = [e for b in batches for e in b.events if type(e).__name__ == "BlockStored"]
    assert stored and stored[0].block_size == 16


def test_abort_over_grpc(rpc):
    async def go():
        req = WorkerGenerateRequest(
            rid="rpc-abort",
            input_ids=list(range(5, 25)),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=100, ignore_eos=True),
        )
        it = rpc.client.generate(req)
        first = await it.__anext__()
        ok = await rpc.client.abort("rpc-abort")
        await it.aclose()
        return first, ok

    first, ok = rpc.run(go())
    assert first.token_ids
    assert ok is True


def test_flush_cache_over_grpc(rpc):
    assert rpc.run(rpc.client.flush_cache()) is True


def test_lora_rpcs_over_grpc(rpc, tmp_path):
    """Load/Unload/ListLoRAAdapter over the wire with an inline npz payload;
    a generate carrying lora_adapter uses it."""
    import io

    import numpy as np

    from smg_tpu.models.lora import empty_adapter

    cfg = rpc.engine.config.model
    rng = np.random.default_rng(5)
    w = empty_adapter(cfg, rank=4)
    for pr in ("wq", "wk", "wv", "wo"):
        w[f"{pr}_a"] = rng.normal(0, 0.5, w[f"{pr}_a"].shape).astype(np.float32)
        w[f"{pr}_b"] = rng.normal(0, 0.5, w[f"{pr}_b"].shape).astype(np.float32)
    buf = io.BytesIO()
    np.savez(buf, **w)

    async def go():
        base_chunks = []
        req = WorkerGenerateRequest(
            rid="lora-base", input_ids=list(range(5, 25)),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=5, ignore_eos=True),
        )
        async for c in rpc.client.generate(req):
            base_chunks.extend(c.token_ids)

        r = await rpc.client.load_lora_adapter("wire-adapter", data=buf.getvalue())
        names = await rpc.client.list_lora_adapters()

        adapted_chunks = []
        req2 = WorkerGenerateRequest(
            rid="lora-on", input_ids=list(range(5, 25)),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=5,
                                    ignore_eos=True, lora_adapter="wire-adapter"),
        )
        async for c in rpc.client.generate(req2):
            adapted_chunks.extend(c.token_ids)
        un = await rpc.client.unload_lora_adapter("wire-adapter")
        return base_chunks, r, names, adapted_chunks, un

    base_chunks, r, names, adapted_chunks, un = rpc.run(go())
    assert r["ok"], r
    assert "wire-adapter" in names
    assert adapted_chunks != base_chunks
    assert un["ok"]


def test_get_tokenizer_bundle_over_grpc(rpc):
    """GetTokenizer streams a bundle the gateway can materialize into a
    working tokenizer (mock descriptor for the test engine)."""
    tok = rpc.run(rpc.client.get_tokenizer())
    assert tok is not None
    assert tok.encode("w5 w6") == [5, 6]
    assert tok.decode([7, 8]) == "w7 w8"


# ---- external DP dispatch (data_parallel_rank; reference
# sglang_scheduler.proto:157-158 + dp_min_token.rs) ----


@pytest.fixture(scope="module")
def dp_rpc():
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=120):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    engines = [make_engine(), make_engine()]
    for e in engines:
        e.start()

    async def _setup():
        server = await serve_worker_async(
            None, port=0, host="127.0.0.1", engines=engines
        )
        client = GrpcWorkerClient(f"127.0.0.1:{server._bound_port}")
        return server, client

    server, client = run(_setup())

    class H:
        pass

    h = H()
    h.run = run
    h.client = client
    h.engines = engines
    yield h
    run(client.close())
    run(server.stop(grace=None))
    loop.call_soon_threadsafe(loop.stop)
    for e in engines:
        e.stop()


def test_dp_model_info_reports_dp_size(dp_rpc):
    info = dp_rpc.run(dp_rpc.client.get_model_info())
    assert info["dp_size"] == 2
    loads = dp_rpc.run(dp_rpc.client.get_loads())
    assert loads["dp_queued_tokens"] == [0, 0]


def test_dp_pinned_rank_routes_to_that_replica(dp_rpc):
    async def go(rank, rid):
        req = WorkerGenerateRequest(
            rid=rid, input_ids=list(range(5, 25)),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=4, ignore_eos=True),
            data_parallel_rank=rank,
        )
        toks = []
        async for ch in dp_rpc.client.generate(req):
            toks.extend(ch.token_ids)
        return toks

    t0 = dp_rpc.run(go(0, "dp-0"))
    t1 = dp_rpc.run(go(1, "dp-1"))
    assert len(t0) == 4 and len(t1) == 4
    # replicas are identical models with identical seeds: same output, and
    # each replica's decode counter moved
    assert dp_rpc.engines[0].scheduler.num_decode_tokens > 0
    assert dp_rpc.engines[1].scheduler.num_decode_tokens > 0


def test_dp_out_of_range_rank_is_an_error(dp_rpc):
    async def go():
        req = WorkerGenerateRequest(
            rid="dp-bad", input_ids=list(range(5, 15)),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=2, ignore_eos=True),
            data_parallel_rank=7,
        )
        async for _ in dp_rpc.client.generate(req):
            pass

    with pytest.raises(RuntimeError, match="out of range"):
        dp_rpc.run(go())


def test_dp_load_manager_and_min_token_policy():
    from smg_tpu.policies.dp import DpLoadManager, MinimumTokensPolicy

    class W:
        worker_id = "w1"
        dp_size = 3

    pol = MinimumTokensPolicy()
    w = W()
    # fills ranks in least-loaded order with atomic increments
    assert pol.select_dp_rank(w, 100) == 0
    assert pol.select_dp_rank(w, 10) == 1
    assert pol.select_dp_rank(w, 10) == 2
    assert pol.select_dp_rank(w, 10) == 1  # 10 < 20 <= 100
    assert pol.manager.loads("w1", 3) == [100, 20, 10]
    pol.release(w, 0, 100)
    assert pol.select_dp_rank(w, 5) == 0
    # dp_size 1 workers are never pinned
    class W1:
        worker_id = "w2"
        dp_size = 1

    assert pol.select_dp_rank(W1(), 50) is None
    # worker-reported baselines shift selection
    mgr = DpLoadManager()
    mgr.seed("w3", [1000, 0])
    assert mgr.select_and_increment_lowest("w3", 2, 10) == 1


# ---- failure isolation over the wire (ISSUE 5) ----


def test_deadline_rides_the_proto_and_times_out(rpc):
    """WorkerGenerateRequest.timeout_secs -> GenerateRequestProto ->
    engine deadline: an exhausted budget comes back as a terminal
    finish_reason='timeout' chunk, not a hung stream."""
    async def go():
        req = WorkerGenerateRequest(
            rid="deadline-1", input_ids=list(range(5, 25)),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=100_000,
                                    ignore_eos=True),
            timeout_secs=0.05,
        )
        chunks = []
        async for chunk in rpc.client.generate(req):
            chunks.append(chunk)
        return chunks

    chunks = rpc.run(go())
    assert chunks[-1].finished
    assert chunks[-1].finish_reason == "timeout"


def test_queue_full_maps_to_resource_exhausted(rpc):
    """Engine QueueFullError -> gRPC RESOURCE_EXHAUSTED -> client
    WorkerQueueFullError (the retryable shape the router keys off)."""
    from smg_tpu.gateway.worker_client import WorkerQueueFullError

    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
            prefill_token_buckets=(32, 64), decode_batch_buckets=(4,),
            max_queued_requests=1,
        ),
        dtype="float32", model_id="tiny-rpc-full",
    )
    engine = Engine(cfg)
    # never started + full queue: every submit rejects deterministically
    engine.submit([1, 2, 3], SamplingParams(max_new_tokens=2), rid="filler")

    async def setup():
        from smg_tpu.rpc.server import serve_worker_async

        server = await serve_worker_async(engine, port=0, host="127.0.0.1")
        client = GrpcWorkerClient(f"127.0.0.1:{server._bound_port}")
        return server, client

    server, client = rpc.run(setup())
    try:
        async def go():
            req = WorkerGenerateRequest(
                rid="q1", input_ids=[4, 5, 6],
                sampling=SamplingParams(max_new_tokens=2),
            )
            async for _ in client.generate(req):
                pass

        with pytest.raises(WorkerQueueFullError):
            rpc.run(go())
    finally:
        rpc.run(client.close())
        rpc.run(server.stop(grace=None))
        engine.stop()


def test_rpc_generate_fault_point_surfaces_as_rpc_error(rpc):
    """The rpc.generate fault seam kills the stream with a gRPC error (the
    shape a crashed servicer produces), and the next request is clean."""
    from smg_tpu.faults import FAULTS

    def gen(rid):
        async def go():
            req = WorkerGenerateRequest(
                rid=rid, input_ids=list(range(5, 15)),
                sampling=SamplingParams(temperature=0.0, max_new_tokens=2,
                                        ignore_eos=True),
            )
            return [c async for c in rpc.client.generate(req)]
        return go

    FAULTS.arm("rpc.generate", mode="once")
    try:
        with pytest.raises(Exception):
            rpc.run(gen("faulted")())
    finally:
        FAULTS.clear()
    chunks = rpc.run(gen("clean-after")())
    assert chunks[-1].finished


def test_health_reflects_engine_health(rpc):
    """HealthCheck answers from engine state: consecutive step failures
    flip it false, recovery flips it back."""
    eng = rpc.engine
    threshold = eng.config.max_consecutive_step_failures
    eng.scheduler.consec_step_failures = threshold
    try:
        assert rpc.run(rpc.client.health()) is False
    finally:
        eng.scheduler.consec_step_failures = 0
    assert rpc.run(rpc.client.health()) is True
