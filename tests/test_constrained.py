"""Structured output: JSON acceptor + token filtering (no jax needed)."""

import json

import pytest

from smg_tpu.constrained import JsonMachine, TokenFilter


@pytest.fixture(scope="module")
def m():
    return JsonMachine()


VALID_PREFIXES = [
    "", "{", '{"', '{"key', '{"key"', '{"key":', '{"key": ', '{"key": 12',
    '{"key": 12.', '{"key": 12.5e', '{"a": [1, 2', '{"a": {"b": tru',
    '[', '[1,', '["x", nul', '  {"a"  :  "b"  ,', '"str with \\', '"esc \\u0A',
    "-", "-1", "12e+",
]

COMPLETE_DOCS = [
    "{}", "[]", '{"a": 1}', '[1, 2, 3]', '"hello"', "true", "null", "42",
    '{"nested": {"x": [1, {"y": "z"}]}}', "-3.5e2",
]

INVALID = [
    "}", "{]", '{"a" 1}', '{"a": 1,}x', "[1 2]", "tru e", '{"a"": 1}',
    '{"a": 01}', "[1,,2]", '"bad \\q"', "{}extra",
]


def test_valid_prefixes(m):
    for p in VALID_PREFIXES:
        assert m.accepts(p), f"should accept prefix: {p!r}"


def test_complete_docs(m):
    for d in COMPLETE_DOCS:
        assert m.accepts(d), f"should accept complete doc: {d!r}"
        assert m.complete(d), f"should be complete: {d!r}"
        json.loads(d)  # sanity


def test_invalid_rejected(m):
    for bad in INVALID:
        assert not m.accepts(bad), f"should reject: {bad!r}"


def test_complete_not_for_prefixes(m):
    for p in ['{"a": 1', "[1, 2", '"unterminated', "12e"]:
        assert not m.complete(p)


def test_token_filter_masks():
    from smg_tpu.tokenizer import MockTokenizer

    class CharTokenizer:
        """One char per token over a small alphabet, for exact mask checks."""

        alphabet = '{}[]":, 0123456789abcdetrulnf-.'

        def decode(self, ids, skip_special_tokens=False):
            return "".join(
                self.alphabet[t - 2] if 2 <= t - 0 and t - 2 < len(self.alphabet) else ""
                for t in ids
            )

    tok = CharTokenizer()
    vocab = len(tok.alphabet) + 2  # 0=eos, 1=unused
    tf = TokenFilter(tok, JsonMachine(), vocab, eos_token_ids={0})

    def allowed_chars(text):
        mask = tf.allowed_mask(text)
        return {tok.alphabet[t - 2] for t in range(2, vocab) if mask[t]}, mask[0]

    chars, eos_ok = allowed_chars("")
    assert "{" in chars and "[" in chars and '"' in chars and "}" not in chars
    assert not eos_ok

    chars, eos_ok = allowed_chars('{"a": 1')
    assert "}" in chars and "," in chars and "0" in chars
    assert "{" not in chars
    assert not eos_ok  # doc not complete yet

    chars, eos_ok = allowed_chars('{"a": 1}')
    assert eos_ok  # complete: eos allowed
    assert chars <= {" "}  # only whitespace may extend

    # mask memoization
    assert tf.allowed_mask('{"a": 1') is tf.allowed_mask('{"a": 1')


def test_guided_generation_simulation(m):
    """Greedy walk under the mask always terminates in valid JSON."""
    from smg_tpu.constrained.token_filter import TokenFilter

    class CharTokenizer:
        alphabet = '{}[]":, 0123456789abcxyz-'

        def decode(self, ids, skip_special_tokens=False):
            return "".join(
                self.alphabet[t - 1] if 1 <= t and t - 1 < len(self.alphabet) else ""
                for t in ids
            )

    tok = CharTokenizer()
    vocab = len(tok.alphabet) + 1
    tf = TokenFilter(tok, m, vocab, eos_token_ids={0})

    # simulate a model that prefers: { " a " : 1 } then eos
    import numpy as np

    preference = list('{"a": 1}') + ["<eos>"]
    text = ""
    for step in range(40):
        mask = tf.allowed_mask(text)
        want = preference[0] if preference else "<eos>"
        if want == "<eos>":
            if mask[0]:
                break
            tid = int(np.argmax(mask))  # fallback: any allowed
        else:
            tid = tok.alphabet.index(want) + 1 if mask[tok.alphabet.index(want) + 1] else int(np.argmax(mask))
        piece = tok.decode([tid])
        text += piece
        if preference and piece == preference[0]:
            preference.pop(0)
    assert m.complete(text), text
    json.loads(text)


# ---- engine integration: the mask actually bites in the decode path ----


class JsonCharTokenizer:
    """One char per token over a JSON-capable alphabet (id 0 = EOS, 1 = BOS).
    Small alphabet keeps the constrained random walk short-lived so sampled
    documents complete (and EOS becomes sampleable) within the token budget."""

    ALPHABET = list('{}[]":, 0123456789')

    def __init__(self):
        self.vocab_size = 512
        self.eos_token_id = 0
        self.bos_token_id = 1
        self.special_ids = {0, 1}

    def encode(self, text, add_special_tokens=False):
        return [
            self.ALPHABET.index(c) + 2 for c in text if c in self.ALPHABET
        ]

    def decode(self, ids, skip_special_tokens=True):
        out = []
        for t in ids:
            if t in self.special_ids:
                continue
            i = t - 2
            out.append(self.ALPHABET[i] if 0 <= i < len(self.ALPHABET) else "\x00")
        return "".join(out)


def _tiny_json_engine():
    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.models.config import tiny_test_config

    cfg = EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=128, auto_size=False, dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4,
            max_seq_len=256,
            max_prefill_tokens=64,
            prefill_token_buckets=(16, 32, 64),
            decode_batch_buckets=(4,),
            decode_horizon=4,  # must collapse to 1 for constrained requests
        ),
        dtype="float32",
    )
    return Engine(cfg, tokenizer=JsonCharTokenizer())


def test_json_constrained_generation_e2e():
    """response_format=json_object ⇒ every sampled stream is a valid JSON
    prefix at temperature 1.0, and stop-finished streams parse."""
    from smg_tpu.protocols.sampling import SamplingParams

    engine = _tiny_json_engine()
    machine = JsonMachine()
    parsed = 0
    for i in range(6):
        sp = SamplingParams(
            temperature=1.0,
            max_new_tokens=96,
            json_schema="{}",  # "any JSON document"
        )
        res = engine.generate(
            prompt_ids=[5, 7, 9, 11], sampling=sp, rid=f"json-{i}"
        )
        assert machine.accepts(res.text), f"invalid JSON prefix: {res.text!r}"
        if res.finish_reason == "stop":
            json.loads(res.text)
            parsed += 1
    # the EOS-when-complete mask makes termination overwhelmingly likely
    assert parsed >= 1, "no constrained stream completed to parseable JSON"


# ---- regex acceptor (r5, VERDICT r4 next-round #9) ----


def test_regex_machine_prefix_and_complete():
    from smg_tpu.constrained.regex_fsm import RegexMachine

    m = RegexMachine(r"[a-c]+[0-9]{2,3}")
    for p in ["", "a", "abc", "abc1", "abc12", "abc123"]:
        assert m.accepts(p), p
    for d in ["a12", "abc123", "cc99"]:
        assert m.complete(d), d
    for bad in ["1", "abcd", "a1234", "abc12x"]:
        assert not m.accepts(bad), bad
    assert not m.complete("abc1")  # needs >= 2 digits

    alt = RegexMachine(r"(yes|no|maybe)?")
    assert alt.complete("") and alt.complete("yes") and alt.complete("maybe")
    assert alt.accepts("ma") and not alt.complete("ma")
    assert not alt.accepts("yesx")

    esc = RegexMachine(r"\d+\.\d+")
    assert esc.complete("3.14") and not esc.accepts("3a")

    neg = RegexMachine(r'"[^"]*"')
    assert neg.complete('"hi"') and neg.accepts('"partial')
    assert not neg.accepts('"a"b')


def test_regex_char_class_escaped_range_endpoints():
    """[\\t-z] must parse as the RANGE \\t..z, not the set {'\\t','-','z'}
    (the old parser flattened the escape and lost the pending range)."""
    from smg_tpu.constrained.regex_fsm import RegexMachine

    m = RegexMachine(r"[\t-z]+")
    for ok in ["\t", "a", "z", " ", "\n", "A9 z"]:  # \n = 0x0a is in range
        assert m.complete(ok), repr(ok)
    assert not m.complete("{") and not m.complete("\x08")

    # escaped HIGH endpoint: '!'..'\\'
    hi = RegexMachine(r"[!-\\]")
    assert hi.complete("!") and hi.complete("\\") and hi.complete("@")
    assert not hi.complete("]")

    # trailing '-' stays literal; class escapes never form ranges
    lit = RegexMachine(r"[a-]")
    assert lit.complete("a") and lit.complete("-")
    digits = RegexMachine(r"[\d-]")
    assert digits.complete("7") and digits.complete("-")
    import pytest as _pytest

    with _pytest.raises(ValueError):
        RegexMachine(r"[a-\d]")  # class escape as range endpoint
    with _pytest.raises(ValueError):
        RegexMachine(r"[z-a]")  # inverted range

    # negated class over an escaped-endpoint range
    negr = RegexMachine(r"[^\t-z]")
    assert negr.complete("{") and not negr.complete("a")


def test_ebnf_machine_prefix_complete_and_recursion():
    from smg_tpu.constrained.ebnf import EbnfMachine, GrammarError

    m = EbnfMachine('''
        root ::= "yes" | "no"
    ''')
    assert m.accepts("") and m.accepts("y") and m.accepts("no")
    assert m.complete("yes") and m.complete("no")
    assert not m.accepts("maybe") and not m.complete("ye")

    # recursion (an NFA can't do this): balanced brackets
    bal = EbnfMachine('''
        root ::= "[" root "]" | "x"
    ''')
    assert bal.complete("x") and bal.complete("[x]") and bal.complete("[[x]]")
    assert bal.accepts("[[") and not bal.complete("[[x]")
    assert not bal.accepts("]")

    # repetition + classes + rule refs
    lst = EbnfMachine('''
        root ::= item ("," item)*
        item ::= [0-9]+
    ''')
    assert lst.complete("1") and lst.complete("12,3,456")
    assert lst.accepts("12,") and not lst.complete("12,")
    assert not lst.accepts("12,,")

    with pytest.raises(GrammarError):
        EbnfMachine('start ::= "x"')  # no root rule
    with pytest.raises(GrammarError):
        EbnfMachine('root ::= missing')  # undefined rule


def test_regex_constrained_generation_e2e():
    """A regex constraint holds over sampled streams end-to-end (the same
    gate json_schema goes through)."""
    from smg_tpu.constrained.regex_fsm import RegexMachine
    from smg_tpu.protocols.sampling import SamplingParams

    engine = _tiny_json_engine()
    pattern = r"[0-9]{3}"
    m = RegexMachine(pattern)
    done = 0
    for i in range(4):
        res = engine.generate(
            prompt_ids=[5, 7, 9], rid=f"rx-{i}",
            sampling=SamplingParams(temperature=1.0, max_new_tokens=12,
                                    regex=pattern),
        )
        assert m.accepts(res.text), res.text
        if res.finish_reason == "stop":
            assert m.complete(res.text)
            done += 1
    assert done >= 1


def test_ebnf_constrained_generation_e2e():
    """ebnf requests are no longer rejected at submit (engine.py) — the
    grammar constrains sampling end-to-end."""
    from smg_tpu.constrained.ebnf import EbnfMachine
    from smg_tpu.protocols.sampling import SamplingParams

    engine = _tiny_json_engine()
    grammar = 'root ::= "[" [0-9] ("," [0-9])* "]"'
    m = EbnfMachine(grammar)
    done = 0
    for i in range(4):
        res = engine.generate(
            prompt_ids=[5, 7, 9], rid=f"eb-{i}",
            sampling=SamplingParams(temperature=1.0, max_new_tokens=16,
                                    ebnf=grammar),
        )
        assert m.accepts(res.text), res.text
        if res.finish_reason == "stop":
            assert m.complete(res.text)
            done += 1
    assert done >= 1


def test_regex_negated_escape_class_and_repeat_cap():
    from smg_tpu.constrained.regex_fsm import RegexMachine

    m = RegexMachine(r"[\S]+")
    assert m.accepts("a") and m.complete("abc")
    assert not m.accepts("a b")  # space is \s
    neg = RegexMachine(r"[^\d]+")
    assert neg.complete("ab") and not neg.accepts("a1")
    with pytest.raises(ValueError, match="repetition bound"):
        RegexMachine(r"a{2000000000}")


def test_malformed_grammar_is_validated_at_gateway():
    from smg_tpu.constrained import validate_grammar

    with pytest.raises(ValueError):
        validate_grammar("[abc", None)
    with pytest.raises(ValueError):
        validate_grammar(None, 'start ::= "x"')  # no root
    validate_grammar(r"[a-z]+", 'root ::= "y"')  # fine
