"""Structured output: JSON acceptor + token filtering (no jax needed)."""

import json

import pytest

from smg_tpu.constrained import JsonMachine, TokenFilter


@pytest.fixture(scope="module")
def m():
    return JsonMachine()


VALID_PREFIXES = [
    "", "{", '{"', '{"key', '{"key"', '{"key":', '{"key": ', '{"key": 12',
    '{"key": 12.', '{"key": 12.5e', '{"a": [1, 2', '{"a": {"b": tru',
    '[', '[1,', '["x", nul', '  {"a"  :  "b"  ,', '"str with \\', '"esc \\u0A',
    "-", "-1", "12e+",
]

COMPLETE_DOCS = [
    "{}", "[]", '{"a": 1}', '[1, 2, 3]', '"hello"', "true", "null", "42",
    '{"nested": {"x": [1, {"y": "z"}]}}', "-3.5e2",
]

INVALID = [
    "}", "{]", '{"a" 1}', '{"a": 1,}x', "[1 2]", "tru e", '{"a"": 1}',
    '{"a": 01}', "[1,,2]", '"bad \\q"', "{}extra",
]


def test_valid_prefixes(m):
    for p in VALID_PREFIXES:
        assert m.accepts(p), f"should accept prefix: {p!r}"


def test_complete_docs(m):
    for d in COMPLETE_DOCS:
        assert m.accepts(d), f"should accept complete doc: {d!r}"
        assert m.complete(d), f"should be complete: {d!r}"
        json.loads(d)  # sanity


def test_invalid_rejected(m):
    for bad in INVALID:
        assert not m.accepts(bad), f"should reject: {bad!r}"


def test_complete_not_for_prefixes(m):
    for p in ['{"a": 1', "[1, 2", '"unterminated', "12e"]:
        assert not m.complete(p)


def test_token_filter_masks():
    from smg_tpu.tokenizer import MockTokenizer

    class CharTokenizer:
        """One char per token over a small alphabet, for exact mask checks."""

        alphabet = '{}[]":, 0123456789abcdetrulnf-.'

        def decode(self, ids, skip_special_tokens=False):
            return "".join(
                self.alphabet[t - 2] if 2 <= t - 0 and t - 2 < len(self.alphabet) else ""
                for t in ids
            )

    tok = CharTokenizer()
    vocab = len(tok.alphabet) + 2  # 0=eos, 1=unused
    tf = TokenFilter(tok, JsonMachine(), vocab, eos_token_ids={0})

    def allowed_chars(text):
        mask = tf.allowed_mask(text)
        return {tok.alphabet[t - 2] for t in range(2, vocab) if mask[t]}, mask[0]

    chars, eos_ok = allowed_chars("")
    assert "{" in chars and "[" in chars and '"' in chars and "}" not in chars
    assert not eos_ok

    chars, eos_ok = allowed_chars('{"a": 1')
    assert "}" in chars and "," in chars and "0" in chars
    assert "{" not in chars
    assert not eos_ok  # doc not complete yet

    chars, eos_ok = allowed_chars('{"a": 1}')
    assert eos_ok  # complete: eos allowed
    assert chars <= {" "}  # only whitespace may extend

    # mask memoization
    assert tf.allowed_mask('{"a": 1') is tf.allowed_mask('{"a": 1')


def test_guided_generation_simulation(m):
    """Greedy walk under the mask always terminates in valid JSON."""
    from smg_tpu.constrained.token_filter import TokenFilter

    class CharTokenizer:
        alphabet = '{}[]":, 0123456789abcxyz-'

        def decode(self, ids, skip_special_tokens=False):
            return "".join(
                self.alphabet[t - 1] if 1 <= t and t - 1 < len(self.alphabet) else ""
                for t in ids
            )

    tok = CharTokenizer()
    vocab = len(tok.alphabet) + 1
    tf = TokenFilter(tok, m, vocab, eos_token_ids={0})

    # simulate a model that prefers: { " a " : 1 } then eos
    import numpy as np

    preference = list('{"a": 1}') + ["<eos>"]
    text = ""
    for step in range(40):
        mask = tf.allowed_mask(text)
        want = preference[0] if preference else "<eos>"
        if want == "<eos>":
            if mask[0]:
                break
            tid = int(np.argmax(mask))  # fallback: any allowed
        else:
            tid = tok.alphabet.index(want) + 1 if mask[tok.alphabet.index(want) + 1] else int(np.argmax(mask))
        piece = tok.decode([tid])
        text += piece
        if preference and piece == preference[0]:
            preference.pop(0)
    assert m.complete(text), text
    json.loads(text)
