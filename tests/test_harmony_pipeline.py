"""Harmony (gpt-oss) serving pipeline (VERDICT r4 next-round #2): channel
-structured prompt building, streaming channel demux with incremental tool
-call argument deltas, and Responses-API integration — e2e through the
router against a scripted worker (reference:
``model_gateway/src/routers/grpc/harmony/{builder,streaming}.rs`` +
``pipeline.rs:1073-1191``)."""

import asyncio
import json

import pytest

from smg_tpu.gateway.harmony import (
    HarmonyStreamingProcessor,
    build_system_message,
    is_harmony_model,
    render_harmony_prompt,
    render_tool_namespace,
)
from smg_tpu.gateway.router import Router, RouterConfig
from smg_tpu.gateway.worker_client import WorkerClient, WorkerStreamChunk
from smg_tpu.gateway.workers import Worker, WorkerRegistry
from smg_tpu.policies import PolicyRegistry
from smg_tpu.protocols.openai import ChatCompletionRequest, ChatMessage
from smg_tpu.tokenizer.registry import TokenizerRegistry

WEATHER_TOOL = {
    "type": "function",
    "function": {
        "name": "get_weather",
        "description": "Get the weather",
        "parameters": {
            "type": "object",
            "properties": {
                "city": {"type": "string", "description": "The city"},
                "unit": {"type": "string", "enum": ["c", "f"]},
            },
            "required": ["city"],
        },
    },
}


# ---- detector ----


def test_detector():
    assert is_harmony_model("gpt-oss-120b")
    assert is_harmony_model("openai/GPT-OSS-20b")
    assert is_harmony_model("gpt_oss_tiny")
    assert not is_harmony_model("llama-3-8b")
    assert not is_harmony_model(None)


# ---- builder ----


def test_system_message_channels_depend_on_tools():
    no_tools = build_system_message(has_tools=False, current_date="2026-07-30")
    with_tools = build_system_message(has_tools=True, current_date="2026-07-30")
    assert "# Valid channels: analysis, final." in no_tools
    assert "commentary" not in no_tools
    assert "# Valid channels: analysis, commentary, final." in with_tools
    assert "commentary channel: 'functions'" in with_tools
    assert "Current date: 2026-07-30" in with_tools
    assert "Reasoning: medium" in with_tools


def test_tool_namespace_typescript_rendering():
    ns = render_tool_namespace([WEATHER_TOOL])
    assert "namespace functions {" in ns
    assert "// Get the weather" in ns
    assert "type get_weather = (_: {" in ns
    assert "// The city" in ns
    assert "city: string," in ns
    assert 'unit?: "c" | "f",' in ns
    assert ns.rstrip().endswith("} // namespace functions")


def test_render_prompt_full_history():
    messages = [
        {"role": "system", "content": "Be terse."},
        {"role": "user", "content": "weather in Paris?"},
        {"role": "assistant", "content": None, "tool_calls": [{
            "id": "call_0", "type": "function",
            "function": {"name": "get_weather", "arguments": '{"city": "Paris"}'},
        }]},
        {"role": "tool", "tool_call_id": "call_0", "content": "18C sunny"},
    ]
    p = render_harmony_prompt(messages, tools=[WEATHER_TOOL],
                              current_date="2026-07-30")
    # system frame: the fixed channel contract, NOT the user system prompt
    assert p.startswith("<|start|>system<|message|>You are ChatGPT")
    # user system prompt lands in the developer instructions
    assert "<|start|>developer<|message|># Instructions\n\nBe terse." in p
    assert "namespace functions {" in p
    assert "<|start|>user<|message|>weather in Paris?<|end|>" in p
    # prior tool call re-renders as a commentary frame
    assert ("<|start|>assistant<|channel|>commentary to=functions.get_weather "
            '<|constrain|>json<|message|>{"city": "Paris"}<|call|>') in p
    # tool result frames as functions.NAME to=assistant
    assert ("<|start|>functions.get_weather to=assistant<|channel|>commentary"
            "<|message|>18C sunny<|end|>") in p
    assert p.endswith("<|start|>assistant")


# ---- streaming demux ----


FRAME_TEXT = (
    "<|channel|>analysis<|message|>user wants weather<|end|>"
    "<|start|>assistant<|channel|>commentary<|message|>Let me check.<|end|>"
    "<|start|>assistant<|channel|>commentary to=functions.get_weather "
    '<|constrain|>json<|message|>{"city": "Paris"}<|call|>'
    "<|start|>assistant<|channel|>final<|message|>It is sunny.<|return|>"
)


@pytest.mark.parametrize("chunk", [1, 3, 7, len(FRAME_TEXT)])
def test_streaming_demux_any_chunking(chunk):
    hp = HarmonyStreamingProcessor()
    analysis = final = args = ""
    names = []
    for i in range(0, len(FRAME_TEXT), chunk):
        d = hp.feed(FRAME_TEXT[i : i + chunk])
        analysis += d.analysis
        final += d.final
        for td in d.tool_deltas:
            if td.name:
                names.append((td.index, td.name, td.id))
            if td.arguments:
                args += td.arguments
    d = hp.flush()
    analysis += d.analysis
    final += d.final
    assert analysis == "user wants weather"
    # plain commentary (preamble) is user-visible, like the final channel
    assert final == "Let me check.It is sunny."
    assert names == [(0, "get_weather", "call_0")]
    assert json.loads(args) == {"city": "Paris"}


def test_streaming_incremental_args():
    """Argument fragments stream as they arrive — not one blob at the end."""
    hp = HarmonyStreamingProcessor()
    head = '<|channel|>commentary to=functions.f<|message|>{"x": '
    d1 = hp.feed(head)
    assert [td.name for td in d1.tool_deltas if td.name] == ["f"]
    frag1 = "".join(td.arguments or "" for td in d1.tool_deltas)
    d2 = hp.feed("1234")
    frag2 = "".join(td.arguments or "" for td in d2.tool_deltas)
    assert frag2  # args flowed before the frame closed
    d3 = hp.feed("}<|call|>")
    frag3 = "".join(td.arguments or "" for td in d3.tool_deltas)
    assert json.loads(frag1 + frag2 + frag3) == {"x": 1234}


def test_parse_full():
    content, reasoning, calls = HarmonyStreamingProcessor().parse_full(FRAME_TEXT)
    assert reasoning == "user wants weather"
    assert content == "Let me check.It is sunny."
    assert len(calls) == 1
    assert calls[0]["name"] == "get_weather"
    assert json.loads(calls[0]["arguments"]) == {"city": "Paris"}


def test_parse_full_unterminated_tool_frame():
    """Stop-string handling eats <|call|> server-side; flush still closes."""
    text = ('<|channel|>analysis<|message|>hm<|end|>'
            '<|start|>assistant<|channel|>commentary to=functions.f'
            '<|message|>{"a": 1}')
    content, reasoning, calls = HarmonyStreamingProcessor().parse_full(text)
    assert reasoning == "hm"
    assert calls[0]["name"] == "f"
    assert json.loads(calls[0]["arguments"]) == {"a": 1}


# ---- router e2e against a scripted worker ----


class CharTokenizer:
    """Round-trips text as code points — lets scripted harmony text survive
    the gateway's real tokenize/detokenize path."""

    eos_token_id = 0
    special_ids: set = set()

    def encode(self, text: str, add_special_tokens: bool = False):
        return [ord(c) for c in text]

    def decode(self, ids, skip_special_tokens: bool = True):
        return "".join(chr(i) for i in ids)

    def apply_chat_template(self, messages, add_generation_prompt=True, **_):
        raise AssertionError("harmony path must not hit the chat template")


class ScriptedWorker(WorkerClient):
    """Streams a scripted completion, a few tokens per chunk; captures the
    prompt it was sent for builder assertions."""

    def __init__(self, script: str, chunk: int = 5):
        self.script = script
        self.chunk = chunk
        self.seen_input_ids = None
        self.seen_sampling = None

    async def generate(self, req):
        self.seen_input_ids = list(req.input_ids)
        self.seen_sampling = req.sampling
        ids = [ord(c) for c in self.script]
        n = max(1, self.chunk)
        for i in range(0, len(ids), n):
            last = i + n >= len(ids)
            yield WorkerStreamChunk(
                rid=req.rid,
                token_ids=ids[i : i + n],
                logprobs=[0.0] * len(ids[i : i + n]),
                finished=last,
                finish_reason="stop" if last else None,
                prompt_tokens=len(self.seen_input_ids),
                output_tokens=min(i + n, len(ids)),
            )

    async def abort(self, rid):
        return True


def _router(script: str):
    registry = WorkerRegistry()
    worker = ScriptedWorker(script)
    registry.add(Worker(worker_id="w0", client=worker, model_id="gpt-oss-tiny"))
    tokenizers = TokenizerRegistry()
    tokenizers.register("gpt-oss-tiny", CharTokenizer(), default=True)
    router = Router(registry, PolicyRegistry(default="round_robin"),
                    tokenizers, RouterConfig())
    return router, worker


def test_router_chat_harmony_tool_call():
    script = (
        "<|channel|>analysis<|message|>need the weather<|end|>"
        "<|start|>assistant<|channel|>commentary to=functions.get_weather "
        '<|constrain|>json<|message|>{"city": "Paris"}<|call|>'
        "LEAKED TEXT PAST THE CALL STOP"  # gateway stop checker must cut this
    )
    router, worker = _router(script)
    req = ChatCompletionRequest(
        model="gpt-oss-tiny",
        messages=[ChatMessage(role="system", content="Be terse."),
                  ChatMessage(role="user", content="weather in Paris?")],
        tools=[WEATHER_TOOL],
    )
    resp = asyncio.run(router.chat(req))
    msg = resp.choices[0].message
    assert msg.reasoning_content == "need the weather"
    assert resp.choices[0].finish_reason == "tool_calls"
    assert msg.tool_calls[0].function.name == "get_weather"
    assert json.loads(msg.tool_calls[0].function.arguments) == {"city": "Paris"}
    assert not (msg.content or "")  # no channel markup leaks
    # the prompt the worker saw was harmony-rendered, not chat-templated
    prompt = "".join(chr(i) for i in worker.seen_input_ids)
    assert prompt.startswith("<|start|>system<|message|>You are ChatGPT")
    assert "namespace functions {" in prompt
    assert prompt.endswith("<|start|>assistant")
    # stop strings are enforced GATEWAY-side: the worker deliberately sees
    # none, and the text the script emitted past <|call|> never surfaced
    assert worker.seen_sampling.stop == []


def test_router_chat_stream_harmony_deltas():
    script = (
        "<|channel|>analysis<|message|>thinking...<|end|>"
        "<|start|>assistant<|channel|>final<|message|>Hello there!<|return|>"
    )
    router, _ = _router(script)
    req = ChatCompletionRequest(
        model="gpt-oss-tiny", stream=True,
        messages=[ChatMessage(role="user", content="hi")],
    )

    async def collect():
        reasoning = content = ""
        finish = None
        async for chunk in router.chat_stream(req):
            d = chunk.choices[0].delta
            reasoning += d.reasoning_content or ""
            content += d.content or ""
            finish = chunk.choices[0].finish_reason or finish
        return reasoning, content, finish

    reasoning, content, finish = asyncio.run(collect())
    assert reasoning == "thinking..."
    assert content == "Hello there!"
    assert finish == "stop"


def test_router_chat_stream_harmony_tool_arg_deltas():
    script = (
        "<|channel|>commentary to=functions.get_weather <|constrain|>json"
        '<|message|>{"city": "Paris", "unit": "c"}<|call|>'
    )
    router, _ = _router(script)
    req = ChatCompletionRequest(
        model="gpt-oss-tiny", stream=True,
        messages=[ChatMessage(role="user", content="weather?")],
        tools=[WEATHER_TOOL],
    )

    async def collect():
        opens, frags, finish = [], [], None
        async for chunk in router.chat_stream(req):
            c = chunk.choices[0]
            for tc in c.delta.tool_calls or []:
                if tc.function.name:
                    opens.append((tc.index, tc.function.name, tc.id))
                if tc.function.arguments:
                    frags.append(tc.function.arguments)
            finish = c.finish_reason or finish
        return opens, frags, finish

    opens, frags, finish = asyncio.run(collect())
    assert opens == [(0, "get_weather", "call_0")]
    assert len(frags) > 1, "arguments must stream incrementally"
    assert json.loads("".join(frags)) == {"city": "Paris", "unit": "c"}
    assert finish == "tool_calls"


def test_harmony_content_parts_flatten():
    """OpenAI content-parts arrays must flatten to text, not leak reprs."""
    p = render_harmony_prompt(
        [{"role": "user",
          "content": [{"type": "text", "text": "hello "},
                      {"type": "text", "text": "world"}]}],
        current_date="2026-07-30",
    )
    assert "<|start|>user<|message|>hello world<|end|>" in p
    assert "{'type'" not in p


def test_harmony_disables_skip_special_tokens():
    """Real gpt-oss tokenizers mark channel tokens special — the demux dies
    if the detokenizer strips them."""
    router, worker = _router("<|channel|>final<|message|>ok<|return|>")
    req = ChatCompletionRequest(
        model="gpt-oss-tiny",
        messages=[ChatMessage(role="user", content="hi")],
    )
    asyncio.run(router.chat(req))
    assert worker.seen_sampling.skip_special_tokens is False


def test_responses_harmony_reasoning_item():
    """Responses API on a harmony model: analysis surfaces as a reasoning
    output item ahead of the message item."""
    from smg_tpu.gateway.responses import ResponsesHandler
    from smg_tpu.protocols.responses import ResponsesRequest

    script = (
        "<|channel|>analysis<|message|>pondering<|end|>"
        "<|start|>assistant<|channel|>final<|message|>Done.<|return|>"
    )
    router, _ = _router(script)
    handler = ResponsesHandler(router)
    req = ResponsesRequest(model="gpt-oss-tiny", input="do the thing", store=False)
    resp = asyncio.run(handler.create(req))
    kinds = [o["type"] for o in resp.output]
    assert kinds == ["reasoning", "message"]
    assert resp.output[0]["content"][0]["text"] == "pondering"
    assert resp.output[1]["content"][0]["text"] == "Done."
