"""Model correctness: the paged prefill/decode serving path must agree with the
dense causal forward (the engine-level analogue of the reference's golden
pipeline-parity tests, ``routers/grpc/pipeline.rs:1194-1436``)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smg_tpu.models import llama
from smg_tpu.models.config import tiny_test_config
from smg_tpu.ops.rope import rope_frequencies


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_test_config()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling))
    return cfg, params, inv_freq


def _empty_cache(cfg, num_pages=32, page_size=16):
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads * cfg.head_dim)
    return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)


def test_prefill_matches_dense(setup):
    cfg, params, inv_freq = setup
    kc, vc = _empty_cache(cfg)
    tokens = jnp.array([5, 6, 7, 8, 9, 10, 11, 12, 13, 14], jnp.int32)
    page_table = jnp.array([1, 2, 0, 0], jnp.int32)
    logits, kc, vc = llama.forward_prefill(
        params, cfg, inv_freq, tokens, jnp.int32(0), jnp.int32(10), kc, vc, page_table
    )
    dense = llama.forward_train(params, cfg, inv_freq, tokens[None])
    np.testing.assert_allclose(np.asarray(logits), np.asarray(dense[0, -1]), atol=1e-5)


def test_prefill_padding_is_inert(setup):
    cfg, params, inv_freq = setup
    tokens = jnp.array([5, 6, 7, 8, 9, 10, 11, 12, 13, 14], jnp.int32)
    page_table = jnp.array([1, 2, 0, 0], jnp.int32)
    kc, vc = _empty_cache(cfg)
    lo_exact, _, _ = llama.forward_prefill(
        params, cfg, inv_freq, tokens, jnp.int32(0), jnp.int32(10), kc, vc, page_table
    )
    kc, vc = _empty_cache(cfg)
    padded = jnp.concatenate([tokens, jnp.full((6,), 7, jnp.int32)])
    lo_pad, _, _ = llama.forward_prefill(
        params, cfg, inv_freq, padded, jnp.int32(0), jnp.int32(10), kc, vc, page_table
    )
    np.testing.assert_allclose(np.asarray(lo_exact), np.asarray(lo_pad), atol=1e-5)


def test_decode_continues_prefill(setup):
    cfg, params, inv_freq = setup
    kc, vc = _empty_cache(cfg)
    prompt = jnp.array([5, 6, 7, 8, 9, 10, 11, 12, 13, 14], jnp.int32)
    page_table = jnp.array([1, 2, 0, 0], jnp.int32)
    _, kc, vc = llama.forward_prefill(
        params, cfg, inv_freq, prompt, jnp.int32(0), jnp.int32(10), kc, vc, page_table
    )
    # decode two tokens; slot 1 is inactive (garbage page 0)
    page_tables = jnp.stack([page_table, jnp.zeros(4, jnp.int32)])
    toks = jnp.array([3, 0], jnp.int32)
    dl, kc, vc = llama.forward_decode(
        params, cfg, inv_freq, toks, jnp.array([10, 0], jnp.int32), kc, vc, page_tables
    )
    dense = llama.forward_train(
        params, cfg, inv_freq, jnp.concatenate([prompt, jnp.array([3], jnp.int32)])[None]
    )
    np.testing.assert_allclose(np.asarray(dl[0]), np.asarray(dense[0, -1]), atol=1e-5)


def test_chunked_prefill_matches_single_shot(setup):
    """Prefill in two chunks (radix-cache style prefix continuation)."""
    cfg, params, inv_freq = setup
    full = jnp.arange(5, 29, dtype=jnp.int32)  # 24 tokens
    page_table = jnp.array([1, 2, 3, 0], jnp.int32)

    kc, vc = _empty_cache(cfg)
    lo_single, _, _ = llama.forward_prefill(
        params, cfg, inv_freq, full, jnp.int32(0), jnp.int32(24), kc, vc, page_table
    )

    kc, vc = _empty_cache(cfg)
    _, kc, vc = llama.forward_prefill(
        params, cfg, inv_freq, full[:16], jnp.int32(0), jnp.int32(16), kc, vc, page_table
    )
    lo_chunk, _, _ = llama.forward_prefill(
        params, cfg, inv_freq, full[16:], jnp.int32(16), jnp.int32(8), kc, vc, page_table
    )
    np.testing.assert_allclose(np.asarray(lo_single), np.asarray(lo_chunk), atol=1e-5)


def test_gqa_and_mha_configs():
    for kv in (1, 2, 8):
        cfg = dataclasses.replace(tiny_test_config(), num_kv_heads=kv, num_layers=2)
        params = llama.init_params(cfg, jax.random.PRNGKey(1))
        inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta, None))
        out = llama.forward_train(params, cfg, inv_freq, jnp.ones((2, 6), jnp.int32))
        assert out.shape == (2, 6, cfg.vocab_size)


def test_llama3_rope_scaling_monotone():
    from smg_tpu.ops.rope import rope_frequencies as rf

    plain = rf(64, 500000.0, None)
    scaled = rf(
        64,
        500000.0,
        {"rope_type": "llama3", "factor": 32.0, "low_freq_factor": 1.0,
         "high_freq_factor": 4.0, "original_max_position_embeddings": 8192},
    )
    assert plain.shape == scaled.shape == (32,)
    assert (scaled <= plain + 1e-9).all()
    assert scaled[-1] < plain[-1]  # low-frequency tail actually scaled down


def test_moe_forward_and_serving():
    """Qwen-MoE family: dense-dispatch MoE MLP through train + serving paths."""
    from smg_tpu.models.config import tiny_moe_config

    cfg = tiny_moe_config()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    assert params["layers"]["router"].shape == (4, 128, 4)
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta, None))
    out = llama.forward_train(params, cfg, inv_freq, jnp.ones((2, 6), jnp.int32))
    assert out.shape == (2, 6, cfg.vocab_size)
    assert bool(jnp.isfinite(out).all())
    # paged serving path must match the dense forward, same as the dense model
    kc, vc = _empty_cache(cfg)
    tokens = jnp.arange(5, 15, dtype=jnp.int32)
    pt = jnp.array([1, 2, 0, 0], jnp.int32)
    lo, kc, vc = llama.forward_prefill(
        params, cfg, inv_freq, tokens, jnp.int32(0), jnp.int32(10), kc, vc, pt
    )
    dense = llama.forward_train(params, cfg, inv_freq, tokens[None])
    np.testing.assert_allclose(np.asarray(lo), np.asarray(dense[0, -1]), atol=1e-4)


def test_moe_engine_e2e():
    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.models.config import tiny_moe_config
    from smg_tpu.protocols.sampling import SamplingParams

    eng = Engine(EngineConfig(
        model=tiny_moe_config(),
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=False, dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=4, max_seq_len=128, max_prefill_tokens=64,
            prefill_token_buckets=(32, 64), decode_batch_buckets=(4,),
        ),
        dtype="float32",
    ))
    res = eng.generate(
        prompt_ids=list(range(5, 25)),
        sampling=SamplingParams(temperature=0.0, max_new_tokens=6, ignore_eos=True),
    )
    assert len(res.token_ids) == 6
