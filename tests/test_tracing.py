"""OTel OTLP/HTTP trace export against a local collector double
(reference: observability/otel_trace.rs; VERDICT r3 next-round #8)."""

import asyncio
import threading

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from smg_tpu.gateway.tracing import OtelTracer, Span, parse_traceparent


def test_parse_traceparent():
    tid, sid = parse_traceparent("00-" + "ab" * 16 + "-" + "cd" * 8 + "-01")
    assert tid == "ab" * 16 and sid == "cd" * 8
    assert parse_traceparent(None) is None
    assert parse_traceparent("garbage") is None
    assert parse_traceparent("00-" + "0" * 32 + "-" + "cd" * 8 + "-01") is None


def test_span_otlp_shape():
    s = Span(name="GET /x", trace_id="ab" * 16)
    s.set("http.request.method", "GET")
    s.set("retries", 2)
    s.set("sampled", True)
    s.end()
    d = s.to_otlp()
    assert d["traceId"] == "ab" * 16 and len(d["spanId"]) == 16
    assert d["status"]["code"] == 1
    attrs = {a["key"]: a["value"] for a in d["attributes"]}
    assert attrs["http.request.method"] == {"stringValue": "GET"}
    assert attrs["retries"] == {"intValue": "2"}
    assert attrs["sampled"] == {"boolValue": True}
    assert int(d["endTimeUnixNano"]) >= int(d["startTimeUnixNano"])


class Collector:
    """OTLP/HTTP collector double."""

    def __init__(self):
        self.batches = []
        self.app = web.Application()
        self.app.router.add_post("/v1/traces", self.handle)

    async def handle(self, request):
        self.batches.append(await request.json())
        return web.json_response({})

    def spans(self):
        out = []
        for b in self.batches:
            for rs in b["resourceSpans"]:
                for ss in rs["scopeSpans"]:
                    out.extend(ss["spans"])
        return out


def test_tracer_batches_and_exports():
    async def go():
        col = Collector()
        runner = web.AppRunner(col.app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        tracer = OtelTracer(f"http://127.0.0.1:{port}", "test-svc",
                            flush_interval=0.05)
        await tracer.start()
        parent = tracer.start_span("parent")
        child = tracer.start_span("child", parent=parent)
        child.end()
        parent.end()
        tracer.record(child)
        tracer.record(parent)
        for _ in range(100):
            if tracer.exported >= 2:
                break
            await asyncio.sleep(0.02)
        await tracer.stop()
        await runner.cleanup()

        spans = col.spans()
        assert len(spans) == 2
        by_name = {s["name"]: s for s in spans}
        assert by_name["child"]["traceId"] == by_name["parent"]["traceId"]
        assert by_name["child"]["parentSpanId"] == by_name["parent"]["spanId"]
        res = col.batches[0]["resourceSpans"][0]["resource"]["attributes"]
        assert {"key": "service.name",
                "value": {"stringValue": "test-svc"}} in res

    asyncio.run(go())


def test_export_failure_never_raises():
    async def go():
        tracer = OtelTracer("http://127.0.0.1:9")  # discard-port: refused
        tracer.record(Span(name="x", trace_id="ab" * 16))
        await tracer.flush()  # must swallow the connection error
        assert tracer.dropped == 1
        await tracer.stop()

    asyncio.run(go())


def test_buffer_cap_drops():
    async def go():
        tracer = OtelTracer("http://127.0.0.1:9", max_buffer=3)
        for _ in range(5):
            tracer.record(Span(name="x", trace_id="ab" * 16))
        assert len(tracer._buffer) == 3 and tracer.dropped == 2
        tracer._buffer.clear()
        await tracer.stop()

    asyncio.run(go())


# ---- gateway e2e: spans for real requests, traceparent propagation ----


def test_gateway_emits_request_spans():
    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.gateway.server import AppContext, build_app
    from smg_tpu.gateway.worker_client import InProcWorkerClient
    from smg_tpu.gateway.workers import Worker
    from smg_tpu.models.config import tiny_test_config
    from smg_tpu.tokenizer import MockTokenizer

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    def run(coro, timeout=300):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=timeout)

    eng = Engine(EngineConfig(
        model=tiny_test_config(),
        cache=CacheConfig(page_size=16, num_pages=64, auto_size=False,
                          dtype="float32"),
        scheduler=SchedulerConfig(
            max_batch_size=2, max_seq_len=128, max_prefill_tokens=32,
            prefill_token_buckets=(32,), decode_batch_buckets=(2,),
        ),
        dtype="float32", model_id="tiny-otel",
    ), tokenizer=MockTokenizer())

    col = Collector()

    async def _setup():
        runner = web.AppRunner(col.app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        ctx = AppContext(policy="round_robin",
                         otel_endpoint=f"http://127.0.0.1:{port}")
        ctx.tracer.flush_interval = 0.05
        ctx.tokenizers.register("tiny-otel", MockTokenizer(), default=True)
        ctx.registry.add(Worker(worker_id="w0", client=InProcWorkerClient(eng),
                                model_id="tiny-otel"))
        tc = TestClient(TestServer(build_app(ctx)))
        await tc.start_server()
        return runner, ctx, tc

    runner, ctx, tc = run(_setup())
    try:
        upstream = "00-" + "12" * 16 + "-" + "34" * 8 + "-01"

        async def go():
            r = await tc.post("/v1/chat/completions", json={
                "model": "tiny-otel",
                "messages": [{"role": "user", "content": "w5"}],
                "max_tokens": 2, "temperature": 0, "ignore_eos": True,
            }, headers={"traceparent": upstream})
            assert r.status == 200
            # the response carries OUR span in traceparent, same trace id
            tp = r.headers.get("traceparent")
            assert tp is not None and tp.split("-")[1] == "12" * 16
            for _ in range(100):
                if ctx.tracer.exported >= 1:
                    return
                await asyncio.sleep(0.02)
            raise TimeoutError("span never exported")

        run(go())
        spans = col.spans()
        chat = [s for s in spans if s["name"] == "POST /v1/chat/completions"]
        assert chat, [s["name"] for s in spans]
        s = chat[0]
        assert s["traceId"] == "12" * 16
        assert s["parentSpanId"] == "34" * 8
        attrs = {a["key"]: a["value"] for a in s["attributes"]}
        assert attrs["http.response.status_code"] == {"intValue": "200"}
        assert attrs["request.id"]["stringValue"].startswith("req-")
    finally:
        run(tc.close())
        run(runner.cleanup())
        loop.call_soon_threadsafe(loop.stop)
        eng.stop()
