#!/usr/bin/env python
"""Metric-name drift check.

Builds the full gateway + engine metric set on a fresh registry, scrapes the
Prometheus exposition, and asserts:

1. every registered ``smg_*`` family appears exactly once (no duplicate
   registration between ``gateway/observability.py`` and
   ``engine/metrics.py``);
2. every exported family is listed in the README observability table, and the
   table names nothing that is no longer exported (docs drift both ways).

Run directly (CI) or through ``tests/test_observability.py``.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
DOC_FILES = [REPO_ROOT / "README.md"]

if str(REPO_ROOT) not in sys.path:  # runnable directly: scripts/check_metric_docs.py
    sys.path.insert(0, str(REPO_ROOT))


def exported_families() -> dict[str, int]:
    """{family_name: occurrences} from a fresh unified registry's exposition.

    Family names are taken from ``# TYPE`` lines — present even for labeled
    metrics with no children yet — and match the text-format convention
    (counters carry the ``_total`` suffix).
    """
    from prometheus_client import CollectorRegistry, generate_latest

    from smg_tpu.engine.metrics import EngineMetrics
    from smg_tpu.gateway.observability import Metrics

    registry = CollectorRegistry()
    Metrics(registry=registry)
    EngineMetrics().register_into(registry)
    counts: dict[str, int] = {}
    for line in generate_latest(registry).decode().splitlines():
        m = re.match(r"# TYPE (smg_\w+) ", line)
        # `_created` companions are prometheus_client bookkeeping emitted
        # alongside every counter/histogram, not families operators consume
        if m and not m.group(1).endswith("_created"):
            counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


def documented_families() -> set[str]:
    """``smg_*`` names from the docs' metric TABLE rows only — a backticked
    mention in prose must not satisfy the check the table exists for."""
    names: set[str] = set()
    for doc in DOC_FILES:
        if not doc.exists():
            continue
        for line in doc.read_text().splitlines():
            m = re.match(r"\|\s*`(smg_\w+)`\s*\|", line)
            if m:
                names.add(m.group(1))
    return names


def check() -> list[str]:
    """Returns a list of human-readable drift errors (empty = clean)."""
    errors: list[str] = []
    counts = exported_families()
    if not counts:
        return ["no smg_* families exported at all (registry wiring broken?)"]
    for name, n in sorted(counts.items()):
        if n != 1:
            errors.append(f"family {name} exported {n} times (expected exactly once)")
    docs = documented_families()
    for name in sorted(counts):
        if name not in docs:
            errors.append(f"family {name} is exported but missing from the docs table")
    for name in sorted(docs - set(counts)):
        errors.append(f"docs table lists {name}, which is no longer exported")
    return errors


def main() -> int:
    errors = check()
    for e in errors:
        print(f"DRIFT: {e}", file=sys.stderr)
    if not errors:
        print(f"ok: {len(exported_families())} smg_* families, docs in sync")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
