#!/usr/bin/env bash
# Single CI entrypoint for the repo's self-checks:
#
#   1. smglint        — AST hot-path & concurrency rules over smg_tpu/
#                       (HOTSYNC / ASYNCBLOCK / LOCKAWAIT / RETRACE plus the
#                       smglint-v2 concurrency set: GUARDED lock-discipline
#                       inference, FRAMEFOLD frame/fold lifecycle, LOCKORDER
#                       acquisition-order inversions, and the smglint-v3
#                       JAX-discipline set: TRACEPURE tracer purity, DONATE
#                       use-after-donate, SHARDDISC sharding commitment —
#                       all in the default set), failing on any unbaselined
#                       finding.  A --changed fast path vs the merge base
#                       runs first for quick signal on PR branches; the full
#                       sweep that follows is the authoritative gate
#                       (cross-module rules like LOCKORDER need it);
#   2. metric docs    — README observability table vs exported smg_* series;
#   3. runtime guards — transfer-guard + zero-recompile probes on the real
#                       engine's steady-state decode loop (the runtime teeth
#                       behind HOTSYNC/RETRACE), via tests/test_analysis.py;
#   3b. program audit — compiled-program auditor on the runner's cached jit
#                       families (the runtime teeth behind TRACEPURE/DONATE/
#                       SHARDDISC): steady-state audited-clean at tp=1 and
#                       tp=8 (0 uncommitted inputs, 0 sharding mismatches,
#                       every intended donation verified-aliased in the
#                       compiled HLO, 0 recompiles while armed), a
#                       deliberately-uncommitted input caught, and recompile
#                       provenance naming the offending argument
#                       (TestProgramAudit in tests/test_analysis.py);
#   4. chunked-prefill scheduling — budgeted-vs-legacy and overlap/sync
#                       stream parity under the per-step prefill budget,
#                       plus mid-prefill preemption/abort lifecycle
#                       (tests/test_chunked_prefill.py + the chunked cases
#                       in tests/test_overlap.py);
#   4b. megastep decode — K-sweep byte-parity vs K=1 (temp 0 and 0.8,
#                       overlap on/off), device done-mask early exit,
#                       quarantine rewind across a megastep, adaptive
#                       horizon controller, 0-recompile at K=8
#                       (tests/test_megastep.py);
#   5. reliability    — engine failure isolation driven through the
#                       smg_tpu/faults.py fault points: poison-step
#                       quarantine (survivor byte-parity + zero leaks),
#                       deadlines, backpressure, watchdog, drain
#                       (tests/test_reliability.py).  The suite runs with
#                       SMG_LOCK_SENTINEL=1: every make_lock-adopted lock
#                       (engine / flight recorder / breaker / worker /
#                       registry / route+SLO observability) joins dynamic
#                       lock-order tracking, and any inversion fails the
#                       offending test at the acquisition that closes the
#                       cycle, with both stacks;
#   6. flight recorder — step-level black box + SLO accounting: ring-bound
#                       under churn, dump-on-quarantine/watchdog/health-flip/
#                       drain via faults.py, DumpFlight RPC + /debug/flight
#                       end-to-end, TTFT failover attribution, /debug/slo
#                       (tests/test_flight_recorder.py);
#   7. routing decisions — gateway decision ring bound + schema,
#                       predicted-vs-actual prefix-hit reconciliation incl.
#                       a fault-injected stale kv index (gateway.kv_event),
#                       KvEventMonitor degraded-mode metrics, /debug/router
#                       + /debug/kv_index end-to-end over in-proc workers,
#                       and per-policy RouteDecision records
#                       (tests/test_route_observability.py + the decision
#                       cases in tests/test_policies.py);
#   8. speculative decoding — fused draft-verify parity: spec-vs-nonspec
#                       byte-parity at temp 0 across overlap modes, spec
#                       overlap-on/off parity at temp 0.8, mid-stream
#                       rejection exactness, quarantine rewind of an
#                       in-flight spec frame, 0-recompile steady state with
#                       spec on, tier/flag plumbing
#                       (tests/test_speculative.py);
#   9. SLO enforcement + loadgen — burn-rate window math, verdict
#                       hysteresis, the SLO-record disconnect-termination
#                       regression, Engine.audit zero-leak surface
#                       (tests/test_slo_enforcement.py), then the SEEDED
#                       loadgen smoke end-to-end (gateway + 2 in-proc
#                       workers, mixed matrix incl. disconnects and
#                       deadline'd requests, ~30s budget with a warm XLA
#                       cache): exits nonzero on ANY SLO-verdict violation,
#                       429-with-breaker-penalty, dropped stream under
#                       drain, missing violation-window flight dump, or
#                       nonzero leak audit at quiescence
#                       (benches/loadgen.py --seed 0 --workers 2);
#   10. tensor-parallel sharded decode — byte-parity vs single-device on
#                       the forced 8-device CPU mesh (temp 0/0.8, overlap
#                       on/off, K∈{1,4}, chunked prefill, speculation),
#                       tp4 kv-head replication fallback, tp8 steady-state
#                       transfer-guard/0-recompile, adaptive-K single
#                       trace, zero-leak audit, donation policy table,
#                       mesh observability surfaces
#                       (tests/test_tp_decode.py).
#
# Usage: scripts/ci_checks.sh
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== smglint (--changed fast path) =="
# Quick signal on the changed subset first; vs the merge base when an
# upstream main exists, else vs HEAD (working tree + untracked).  The full
# sweep below stays the authoritative gate — cross-module rules (LOCKORDER)
# only see pairs inside the changed subset here.
MERGE_BASE=$(git merge-base HEAD origin/main 2>/dev/null \
    || git merge-base HEAD main 2>/dev/null || echo HEAD)
python scripts/smglint.py --changed "$MERGE_BASE"

echo "== smglint (full sweep — authoritative) =="
python scripts/smglint.py smg_tpu/

echo "== metric docs drift =="
JAX_PLATFORMS=cpu python scripts/check_metric_docs.py

echo "== lint rule suite + runtime guard probes =="
JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q -m 'not slow' \
    -k 'not TestProgramAudit' -p no:cacheprovider

echo "== program audit (compiled-program auditor, tp=1 + tp=8) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q -m 'not slow' \
    -k TestProgramAudit -p no:cacheprovider

echo "== chunked-prefill scheduling parity =="
JAX_PLATFORMS=cpu python -m pytest tests/test_chunked_prefill.py \
    tests/test_overlap.py -q -m 'not slow' -p no:cacheprovider

echo "== megastep decode K-sweep parity =="
JAX_PLATFORMS=cpu python -m pytest tests/test_megastep.py -q \
    -m 'not slow' -p no:cacheprovider

echo "== reliability / failure isolation (lock-order sentinel armed) =="
JAX_PLATFORMS=cpu SMG_LOCK_SENTINEL=1 python -m pytest tests/test_reliability.py -q \
    -m 'not slow' -p no:cacheprovider

echo "== flight recorder / SLO accounting =="
JAX_PLATFORMS=cpu python -m pytest tests/test_flight_recorder.py -q \
    -m 'not slow' -p no:cacheprovider

echo "== routing decision observability =="
JAX_PLATFORMS=cpu python -m pytest tests/test_route_observability.py \
    tests/test_policies.py -q -m 'not slow' -p no:cacheprovider

echo "== speculative decoding (fused draft-verify) parity =="
JAX_PLATFORMS=cpu python -m pytest tests/test_speculative.py -q \
    -m 'not slow' -p no:cacheprovider

echo "== SLO enforcement + seeded loadgen smoke =="
JAX_PLATFORMS=cpu python -m pytest tests/test_slo_enforcement.py -q \
    -m 'not slow' -p no:cacheprovider
JAX_PLATFORMS=cpu python benches/loadgen.py --seed 0 --workers 2

echo "== tensor-parallel sharded decode (8-device CPU mesh) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_tp_decode.py -q \
    -m 'not slow' -p no:cacheprovider

echo "ci_checks: all green"
