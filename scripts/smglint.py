#!/usr/bin/env python
"""Repo-native static analysis: hot-path sync, async-blocking, lock-domain,
jit-retrace, lock-discipline (GUARDED), frame/fold lifecycle (FRAMEFOLD),
lock-order inversion (LOCKORDER), and JAX-discipline (TRACEPURE tracer
purity, DONATE use-after-donate, SHARDDISC sharding commitment) hazards.
Thin wrapper so CI can run it without installing the package; the
implementation lives in ``smg_tpu/analysis/``.

    python scripts/smglint.py smg_tpu/
    python scripts/smglint.py --changed              # pre-commit fast path
    python scripts/smglint.py --changed origin/main  # vs a merge base
    python scripts/smglint.py smg_tpu/ --write-baseline
    python scripts/smglint.py smg_tpu/gateway --rules GUARDED,LOCKORDER
    python scripts/smglint.py smg_tpu/ --format sarif   # CI diff annotation
"""

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from smg_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
