#!/usr/bin/env python
"""Generate an OpenAPI 3.1 spec from the protocol models.

Reference: the reference generates client SDKs from its Rust protocol types
via OpenAPI (``clients/openapi-gen``, ``Makefile:151-189``); here the pydantic
models are the single source of truth.

Usage: python scripts/gen_openapi.py [out.json]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_spec() -> dict:
    from pydantic.json_schema import models_json_schema

    from smg_tpu.protocols.anthropic import (
        AnthropicMessagesRequest,
        AnthropicMessagesResponse,
    )
    from smg_tpu.protocols.generate import GenerateRequest, GenerateResponse
    from smg_tpu.protocols.openai import (
        ChatCompletionRequest,
        ChatCompletionResponse,
        ChatCompletionStreamChunk,
        CompletionRequest,
        CompletionResponse,
        EmbeddingRequest,
        EmbeddingResponse,
        ErrorResponse,
        ModelList,
    )
    from smg_tpu.protocols.rerank import (
        ClassifyRequest,
        ClassifyResponse,
        RerankRequest,
        RerankResponse,
    )
    from smg_tpu.protocols.responses import ResponsesRequest, ResponsesResponse
    from smg_tpu.version import __version__

    models = [
        ChatCompletionRequest, ChatCompletionResponse, ChatCompletionStreamChunk,
        CompletionRequest, CompletionResponse,
        EmbeddingRequest, EmbeddingResponse,
        AnthropicMessagesRequest, AnthropicMessagesResponse,
        ResponsesRequest, ResponsesResponse,
        GenerateRequest, GenerateResponse,
        RerankRequest, RerankResponse, ClassifyRequest, ClassifyResponse,
        ModelList, ErrorResponse,
    ]
    from smg_tpu.protocols.interactions import Interaction, InteractionsRequest
    from smg_tpu.protocols.transcription import TranscriptionResponse

    models += [InteractionsRequest, Interaction, TranscriptionResponse]
    _, defs = models_json_schema(
        [(m, "validation") for m in models],
        ref_template="#/components/schemas/{model}",
    )
    schemas = defs.get("$defs", {})

    def op(tag, summary, req_model=None, resp_model=None, streaming=False):
        o = {
            "tags": [tag],
            "summary": summary + (" (set stream=true for SSE)" if streaming else ""),
            "responses": {
                "200": {"description": "OK"},
                "400": {"$ref": "#/components/responses/Error"},
            },
        }
        if req_model:
            o["requestBody"] = {
                "required": True,
                "content": {"application/json": {
                    "schema": {"$ref": f"#/components/schemas/{req_model}"}}},
            }
        if resp_model:
            o["responses"]["200"] = {
                "description": "OK",
                "content": {"application/json": {
                    "schema": {"$ref": f"#/components/schemas/{resp_model}"}}},
            }
        return o

    paths = {
        "/v1/chat/completions": {"post": op(
            "openai", "Chat completion", "ChatCompletionRequest",
            "ChatCompletionResponse", streaming=True)},
        "/v1/completions": {"post": op(
            "openai", "Text completion", "CompletionRequest",
            "CompletionResponse", streaming=True)},
        "/v1/embeddings": {"post": op(
            "openai", "Embeddings", "EmbeddingRequest", "EmbeddingResponse")},
        "/v1/rerank": {"post": op(
            "native", "Rerank documents", "RerankRequest", "RerankResponse")},
        "/v1/classify": {"post": op(
            "native", "Classify inputs", "ClassifyRequest", "ClassifyResponse")},
        "/v1/messages": {"post": op(
            "anthropic", "Anthropic Messages", "AnthropicMessagesRequest",
            "AnthropicMessagesResponse", streaming=True)},
        "/v1/responses": {"post": op(
            "openai", "Responses API (agentic, MCP tool loop)",
            "ResponsesRequest", "ResponsesResponse", streaming=True)},
        "/generate": {"post": op(
            "native", "Native generate (SGLang-compatible)",
            "GenerateRequest", "GenerateResponse", streaming=True)},
        "/v1/models": {"get": op("openai", "List models", None, "ModelList")},
        "/v1/tokenize": {"post": op("native", "Tokenize text")},
        "/v1/detokenize": {"post": op("native", "Detokenize ids")},
        "/parse/function_call": {"post": op("native", "Parse tool calls from text")},
        "/parse/reasoning": {"post": op("native", "Split reasoning from text")},
        "/health": {"get": op("ops", "Liveness probe")},
        "/readiness": {"get": op("ops", "Readiness probe")},
        "/health_generate": {"get": op("ops", "End-to-end generation probe")},
        "/metrics": {"get": op("ops", "Prometheus metrics")},
        "/get_loads": {"get": op("ops", "Per-worker engine loads")},
        "/flush_cache": {"post": op("ops", "Flush prefix caches")},
        "/workers": {
            "get": op("ops", "List workers"),
            "post": op("ops", "Register a gRPC worker"),
        },
        "/v1/conversations": {"post": op("openai", "Create conversation")},
        "/v1/interactions": {"post": op(
            "native", "Interactions API (stateful, chained turns)",
            "InteractionsRequest", "Interaction", streaming=True)},
        "/v1/audio/transcriptions": {"post": op(
            "openai",
            "Audio transcription (multipart/form-data: file + fields)",
            None, "TranscriptionResponse")},
    }

    return {
        "openapi": "3.1.0",
        "info": {
            "title": "smg-tpu gateway API",
            "version": __version__,
            "description": "TPU-native LLM serving: OpenAI/Anthropic-compatible "
                           "APIs over an in-tree JAX/XLA/Pallas engine.",
        },
        "paths": paths,
        "components": {
            "schemas": schemas,
            "responses": {
                "Error": {
                    "description": "Error",
                    "content": {"application/json": {
                        "schema": {"$ref": "#/components/schemas/ErrorResponse"}}},
                }
            },
        },
    }


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "openapi.json"
    spec = build_spec()
    with open(out, "w") as f:
        json.dump(spec, f, indent=2)
    print(f"wrote {out}: {len(spec['paths'])} paths, "
          f"{len(spec['components']['schemas'])} schemas")
