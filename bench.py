"""Benchmark: decode throughput + TTFT of the in-tree TPU engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Measures, on a Llama-3.2-1B-class model (bf16, random weights — tokenizer-free
token-id workload, which is exactly what the gateway's gRPC path ships to
workers; SURVEY.md §0 "workers only see token IDs"):

  * steady-state decode tokens/sec/chip through the full engine
    (continuous-batching scheduler + paged KV + fused sampling),
  * prefill TTFT for a 512-token prompt (post-compile, the serving number),
  * a long-context (4096-token) kernel A/B: Pallas page-streaming decode
    attention vs the XLA gather path, at the shape where the gather
    materializes ~131k tokens per layer,
  * an HBM roofline accounting (decode is memory-bound: every step re-reads
    the weights plus the live KV pages) against the v5e's 819 GB/s.

Baseline: the reference's CI-gated e2e floor is 12 output tok/s per request
stream (BASELINE.md, `test_regular_perf.py:27`) with ~32 concurrent requests
per H100 worker => ~384 tok/s/GPU floor.  vs_baseline = value / 384.

HONESTY CONTRACT (the round-2 lesson): the bench slot records TPU numbers
only.  The ambient remote-TPU PJRT plugin is flaky, so the probe retries over
several minutes — but if the TPU truly cannot initialize, this script emits
``{"metric": "tpu_unavailable", ...}`` and exits non-zero instead of dressing
a CPU smoke run up as a result.  (The CPU smoke still runs for diagnostics
and is embedded under ``"cpu_smoke"`` — clearly labelled, never the metric.)

Process hygiene: the __main__ orchestrator never imports jax itself — a
wedged plugin tunnel can hang ``import jax`` for every process that inherits
the ambient environment.  Probing and measuring happen in bounded child
processes; the CPU child gets a sanitized env (sitecustomize stripped,
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# v5e HBM bandwidth, bytes/sec — roofline denominator.
_HBM_BYTES_PER_SEC = {"tpu": 819e9, "cpu": None}
_BASELINE_TOK_S = 384.0  # reference CI floor: 12 tok/s/stream x 32 streams

# single source of truth for env sanitation lives next to the other driver
# entry point; both files sit at the repo root so this import always resolves
from __graft_entry__ import _repo_root, _sanitized_env  # noqa: E402


def _probe_tpu(timeouts: tuple = (120.0, 90.0, 60.0, 60.0, 60.0),
               sleep_between: float = 15.0) -> bool:
    """True iff a TPU backend initializes in a subprocess within bounds.
    Retries over ~6.5 minutes: the plugin tunnel is flaky, not absent."""
    code = (
        "import jax; ds = jax.devices(); "
        "print('PLATFORMS:' + ','.join(sorted({d.platform for d in ds})))"
    )
    for i, timeout_s in enumerate(timeouts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout_s,
                cwd=_repo_root(),
            )
            if r.returncode == 0 and "tpu" in r.stdout:
                return True
            sys.stderr.write(
                f"[bench] probe {i + 1}/{len(timeouts)}: rc={r.returncode} "
                f"out={r.stdout.strip()!r} err={r.stderr.strip()[-200:]!r}\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"[bench] probe {i + 1}/{len(timeouts)}: timeout {timeout_s}s\n")
        if i < len(timeouts) - 1:
            time.sleep(sleep_between)
    return False


def _roofline(param_bytes: int, kv_bytes_per_step: float, steps_per_sec: float,
              on_tpu: bool) -> tuple[float, float | None]:
    hbm_gbps = steps_per_sec * (param_bytes + kv_bytes_per_step) / 1e9
    peak = _HBM_BYTES_PER_SEC["tpu" if on_tpu else "cpu"]
    util = round(hbm_gbps * 1e9 / peak, 4) if peak else None
    return round(hbm_gbps, 2), util


def main(on_tpu: bool) -> None:
    import jax
    import numpy as np

    if not on_tpu:
        # belt-and-braces: pin default device to CPU even if some other
        # backend slipped through
        jax.config.update("jax_default_device", jax.devices("cpu")[0])

    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.models.config import llama32_1b_config, tiny_test_config
    from smg_tpu.protocols.sampling import SamplingParams

    if on_tpu:
        model_cfg = llama32_1b_config()
        batch, prompt_len, gen_len = 32, 128, 64
        max_seq = 4096  # headroom for the long-context kernel A/B
        pages = 32 * (max_seq // 16) + 64
        dtype = "bfloat16"
        horizon = 16
    else:
        model_cfg = tiny_test_config()
        batch, prompt_len, gen_len = 8, 32, 16
        max_seq = 128
        pages = 128
        dtype = "float32"
        horizon = 4

    cfg = EngineConfig(
        model=model_cfg,
        cache=CacheConfig(page_size=16, num_pages=pages, auto_size=False, dtype=dtype),
        scheduler=SchedulerConfig(
            max_batch_size=batch,
            max_seq_len=max_seq,
            max_prefill_tokens=512 if on_tpu else 64,
            prefill_token_buckets=(128, 256, 512) if on_tpu else (32, 64),
            decode_batch_buckets=(batch,),
            decode_horizon=horizon,
        ),
        dtype=dtype,
    )
    engine = Engine(cfg)
    ps = cfg.cache.page_size

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(10, model_cfg.vocab_size - 10, prompt_len).tolist()
        for _ in range(batch)
    ]
    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_len, ignore_eos=True)

    def run_round(tag: str) -> tuple[float, int]:
        finished = set()

        def cb(out):
            if out.finished:
                finished.add(out.rid)

        for i, p in enumerate(prompts):
            engine.submit(p, sp, rid=f"{tag}-{i}", on_output=cb)
        t0 = time.perf_counter()
        start_decode = engine.scheduler.num_decode_tokens
        while len(finished) < batch:
            engine.step()
            if time.perf_counter() - t0 > 600:
                raise TimeoutError(f"bench stuck: {engine.loads()}")
        dt = time.perf_counter() - t0
        return dt, engine.scheduler.num_decode_tokens - start_decode

    run_round("warmup")  # compile
    engine.flush_cache()

    # ---- TTFT: one 512-token prompt, post-compile (the serving number) ----
    ttft_len = 512 if on_tpu else 32
    ttft_prompt = rng.integers(10, model_cfg.vocab_size - 10, ttft_len).tolist()
    got_first = []

    def ttft_cb(out):
        if out.new_token_ids and not got_first:
            got_first.append(time.perf_counter())

    ttft_ms = None
    for rep in range(2):  # rep 0 warms the single-request prefill shape
        got_first.clear()
        engine.submit(ttft_prompt, SamplingParams(temperature=0.0, max_new_tokens=4,
                                                  ignore_eos=True),
                      rid=f"ttft-{rep}", on_output=ttft_cb)
        t0 = time.perf_counter()
        while not got_first:
            engine.step()
            if time.perf_counter() - t0 > 300:
                raise TimeoutError("ttft measurement stuck")
        ttft_ms = (got_first[0] - t0) * 1e3
        for _ in range(gen_len):  # drain
            if not engine.scheduler.has_work():
                break
            engine.step()
        engine.flush_cache()

    # ---- steady-state decode throughput through the full engine ----
    dt, _ = run_round("bench")
    total_new = batch * gen_len
    tput = total_new / dt

    param_bytes = sum(x.nbytes for x in jax.tree.leaves(engine.runner.params))
    kv_itemsize = 2 if dtype == "bfloat16" else 4
    kv_bytes_tok = (model_cfg.num_layers * model_cfg.num_kv_heads
                    * model_cfg.head_dim * 2 * kv_itemsize)
    mean_ctx = prompt_len + gen_len / 2
    hbm_gbps, hbm_util = _roofline(
        param_bytes, batch * mean_ctx * kv_bytes_tok, tput / batch, on_tpu
    )

    # ---- long-context kernel A/B: pallas page-streaming vs XLA gather ----
    # Direct runner.decode_multi at B x 4096-token contexts — the shape where
    # the gather path materializes B*mp*ps tokens per layer.  Flipping
    # runner.attn_impl + clearing the compile cache swaps the kernel under an
    # otherwise identical jitted step.
    long_ctx = {}
    if on_tpu:
        runner = engine.runner
        mp = max_seq // ps  # 256 pages -> 4096-token context
        perm = rng.permutation(pages - 1)[: batch * mp] + 1  # skip garbage page 0
        page_tables = perm.reshape(batch, mp).astype(np.int32)
        toks = np.ones(batch, np.int32)
        pos = np.full(batch, max_seq - horizon - 1, np.int32)
        temps = np.zeros(batch, np.float32)
        topks = np.full(batch, -1, np.int32)
        topps = np.ones(batch, np.float32)
        minps = np.zeros(batch, np.float32)
        kv_long = batch * (max_seq - horizon) * kv_bytes_tok

        # a 512-token chunk extending a ~3.5k-token cached prefix: the shape
        # where the XLA prefill gathers the full 4096-token worst case but
        # the paged kernel streams only the live prefix pages
        chunk = rng.integers(10, model_cfg.vocab_size - 10, 512).tolist()
        prefix_len = max_seq - 520
        pt_one = page_tables[0]

        saved_impl = runner.attn_impl
        for impl in ("pallas", "xla"):
            runner.attn_impl = impl
            runner.invalidate_compiled("decode_multi")
            runner.invalidate_compiled("prefill")
            try:
                runner.decode_multi(toks, pos, page_tables, temps, topks, topps,
                                    minps, horizon)  # compile
                reps, t0 = 8, time.perf_counter()
                for _ in range(reps):
                    runner.decode_multi(toks, pos, page_tables, temps, topks,
                                        topps, minps, horizon)
                dt_k = (time.perf_counter() - t0) / reps
                k_tput = batch * horizon / dt_k
                g, u = _roofline(param_bytes, kv_long, k_tput / batch, on_tpu)
                long_ctx[impl] = {"tok_s": round(k_tput, 2), "hbm_gbps": g,
                                  "hbm_util": u}
            except Exception as e:  # a kernel failure must not void the bench
                long_ctx[impl] = {"error": f"{type(e).__name__}: {e}"[:300]}
                continue
            try:
                runner.prefill(chunk, prefix_len, pt_one, 0.0, -1, 1.0, 0.0)
                reps, t0 = 4, time.perf_counter()
                for _ in range(reps):
                    runner.prefill(chunk, prefix_len, pt_one, 0.0, -1, 1.0, 0.0)
                long_ctx[impl]["warm_prefill_512_ms"] = round(
                    (time.perf_counter() - t0) / reps * 1e3, 1
                )
            except Exception as e:
                long_ctx[impl]["warm_prefill_512_ms"] = f"{type(e).__name__}: {e}"[:200]
        runner.attn_impl = saved_impl

    result = {
        "metric": "decode_tokens_per_sec_per_chip"
        if on_tpu
        else "decode_tokens_per_sec_cpu_smoke",
        "value": round(tput, 2),
        "unit": "tok/s",
        "vs_baseline": round(tput / _BASELINE_TOK_S, 3),
        "platform": "tpu" if on_tpu else "cpu",
        "ttft_ms_512tok" if on_tpu else "ttft_ms_32tok": round(ttft_ms, 1),
        "hbm_gbps": hbm_gbps,
        "hbm_util": hbm_util,
        "long_ctx_4096": long_ctx or None,
        "batch": batch,
        "gen_len": gen_len,
        "param_bytes": param_bytes,
    }
    print(json.dumps(result))


def _salvage_result(stdout) -> dict | None:
    """Return the last valid result record from a child's captured stdout.
    A child that completed its measurement but died/stalled in teardown (the
    wedged-plugin scenario) still gets its number recorded."""
    if not stdout:
        return None
    if isinstance(stdout, bytes):
        stdout = stdout.decode(errors="replace")
    for line in reversed(stdout.splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            return rec
    return None


def _run_child(mode: str, timeout_s: float) -> dict | None:
    """Run the benchmark child; return its result record (stderr streams
    through for progress).  Teardown stalls/crashes after the result line are
    tolerated via _salvage_result."""
    env = dict(os.environ) if mode == "tpu" else _sanitized_env()
    env["SMG_BENCH_MODE"] = mode
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            cwd=_repo_root(),
            timeout=timeout_s,
            stdout=subprocess.PIPE,
            text=True,
        )
    except subprocess.TimeoutExpired as e:
        return _salvage_result(e.stdout)
    return _salvage_result(r.stdout)


if __name__ == "__main__":
    mode = os.environ.get("SMG_BENCH_MODE")
    if mode:
        main(on_tpu=(mode == "tpu"))
        sys.exit(0)
    if _probe_tpu():
        rec = _run_child("tpu", timeout_s=2400)
        if rec is not None:
            print(json.dumps(rec))
            sys.exit(0)
        sys.stderr.write("[bench] TPU child produced no result\n")
    # TPU unavailable or the TPU run failed: say so — the CPU smoke is a
    # diagnostic embedded in the record, never the headline metric.
    smoke = _run_child("cpu", timeout_s=900)
    # deterministic engine gate (fixed seeds + stream fingerprint): the
    # round-over-round regression record while the TPU stays unreachable
    gate = None
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(_repo_root(), "benches", "bench_engine.py")],
            env=_sanitized_env(), cwd=_repo_root(), timeout=900,
            stdout=subprocess.PIPE, text=True,
        )
        gate = _salvage_result(r.stdout) or (
            json.loads(r.stdout.strip().splitlines()[-1]) if r.stdout.strip() else None
        )
    except Exception as e:
        gate = {"error": f"{type(e).__name__}: {e}"[:200]}
    # routing-decision probe (benches/bench_gateway.py --routing-probe):
    # prefix-hit rate + prediction error, cache_aware vs round_robin on a
    # Zipf multi-turn trace, and the decision-ring hot-path overhead cap
    routing = None
    try:
        r = subprocess.run(
            [sys.executable,
             os.path.join(_repo_root(), "benches", "bench_gateway.py"),
             "--routing-probe"],
            env=_sanitized_env(), cwd=_repo_root(), timeout=600,
            stdout=subprocess.PIPE, text=True,
        )
        if r.returncode != 0:
            raise RuntimeError(f"bench_gateway exited {r.returncode}")
        routing = {}
        for line in r.stdout.strip().splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "bench" in rec:
                routing[rec.pop("bench")] = rec
    except Exception as e:
        routing = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps({
        "metric": "tpu_unavailable",
        "value": 0.0,
        "unit": "tok/s",
        "vs_baseline": 0.0,
        "detail": "TPU backend failed to initialize (probe retried ~6min) "
                  "or the TPU bench child produced no result",
        "cpu_smoke": smoke,
        "engine_gate": gate,
        "routing_probe": routing,
    }))
    sys.exit(1)
