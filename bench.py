"""Benchmark: decode throughput of the in-tree TPU engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures steady-state decode tokens/sec/chip through the full engine
(continuous-batching scheduler + paged KV + fused sampling) on a
Llama-3.2-1B-class model (bf16, random weights — tokenizer-free token-id
workload, which is exactly what the gateway's gRPC path ships to workers;
SURVEY.md §0 "workers only see token IDs").

Baseline: the reference's CI-gated e2e floor is 12 output tok/s per request
stream (BASELINE.md, `test_regular_perf.py:27`) with ~32 concurrent requests
per H100 worker => ~384 tok/s/GPU floor.  vs_baseline = value / 384.
On non-TPU hosts this still runs (tiny model) but reports the TPU metric name
with a "cpu-smoke" suffix so results are never confused.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def main() -> None:
    on_tpu = any(d.platform == "tpu" for d in jax.devices())

    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.models.config import llama32_1b_config, tiny_test_config
    from smg_tpu.protocols.sampling import SamplingParams

    if on_tpu:
        model_cfg = llama32_1b_config()
        batch, prompt_len, gen_len = 32, 128, 64
        max_seq = 1024
        pages = 32 * (max_seq // 16) + 64
        dtype = "bfloat16"
        horizon = 16
    else:
        model_cfg = tiny_test_config()
        batch, prompt_len, gen_len = 8, 32, 16
        max_seq = 128
        pages = 128
        dtype = "float32"
        horizon = 4

    cfg = EngineConfig(
        model=model_cfg,
        cache=CacheConfig(page_size=16, num_pages=pages, auto_size=False, dtype=dtype),
        scheduler=SchedulerConfig(
            max_batch_size=batch,
            max_seq_len=max_seq,
            max_prefill_tokens=512 if on_tpu else 64,
            prefill_token_buckets=(128, 256, 512) if on_tpu else (32, 64),
            decode_batch_buckets=(batch,),
            decode_horizon=horizon,
        ),
        dtype=dtype,
    )
    engine = Engine(cfg)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(10, model_cfg.vocab_size - 10, prompt_len).tolist() for _ in range(batch)]
    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_len, ignore_eos=True)

    def run_round(tag: str) -> tuple[float, int]:
        finished = set()

        def cb(out, rid_box=[None]):
            if out.finished:
                finished.add(out.rid)

        for i, p in enumerate(prompts):
            engine.submit(p, sp, rid=f"{tag}-{i}", on_output=cb)
        # prefill phase (admission happens inside step)
        t0 = time.perf_counter()
        decode_tokens = 0
        start_decode = engine.scheduler.num_decode_tokens
        while len(finished) < batch:
            engine.step()
            if time.perf_counter() - t0 > 600:
                raise TimeoutError(f"bench stuck: {engine.loads()}")
        dt = time.perf_counter() - t0
        decode_tokens = engine.scheduler.num_decode_tokens - start_decode
        return dt, decode_tokens

    # warmup (compile)
    run_round("warmup")
    engine.flush_cache()

    dt, decode_tokens = run_round("bench")
    total_new = batch * gen_len
    tput = total_new / dt

    baseline = 384.0  # reference CI floor: 12 tok/s/stream x 32 streams per chip
    result = {
        "metric": "decode_tokens_per_sec_per_chip" if on_tpu else "decode_tokens_per_sec_cpu_smoke",
        "value": round(tput, 2),
        "unit": "tok/s",
        "vs_baseline": round(tput / baseline, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
