"""Benchmark: decode throughput of the in-tree TPU engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Measures steady-state decode tokens/sec/chip through the full engine
(continuous-batching scheduler + paged KV + fused sampling) on a
Llama-3.2-1B-class model (bf16, random weights — tokenizer-free token-id
workload, which is exactly what the gateway's gRPC path ships to workers;
SURVEY.md §0 "workers only see token IDs").

Baseline: the reference's CI-gated e2e floor is 12 output tok/s per request
stream (BASELINE.md, `test_regular_perf.py:27`) with ~32 concurrent requests
per H100 worker => ~384 tok/s/GPU floor.  vs_baseline = value / 384.

Robustness (the round-1 lesson): this host carries an always-on remote-TPU
PJRT plugin registered by an ambient sitecustomize that, when its tunnel is
wedged, makes ``import jax``/``jax.devices()`` hang or raise for EVERY
process that inherits the ambient environment.  So the __main__ guard is an
orchestrator that never imports jax itself: it probes the backend in a
throwaway subprocess with a hard timeout (one retry — the tunnel
occasionally drops a request), then runs the real benchmark in a child
process either on TPU (ambient env, probe proved it healthy) or on CPU
(sanitized env: sitecustomize entry stripped from PYTHONPATH, plugin's
trigger env var removed, JAX_PLATFORMS=cpu).  A TPU child that dies or
stalls mid-run falls back to the CPU child, so a JSON line is always
emitted with rc=0.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# v5e HBM bandwidth, bytes/sec — roofline denominator for the utilization
# metric (decode is memory-bound: each model step re-reads the weights and
# the active KV pages).
_HBM_BYTES_PER_SEC = {"tpu": 819e9, "cpu": None}
_BASELINE_TOK_S = 384.0  # reference CI floor: 12 tok/s/stream x 32 streams


# single source of truth for env sanitation lives next to the other driver
# entry point; both files sit at the repo root so this import always resolves
from __graft_entry__ import _repo_root, _sanitized_env  # noqa: E402


def _probe_tpu(timeouts: tuple = (120.0, 60.0)) -> bool:
    """True iff a TPU backend initializes in a subprocess within bounds."""
    code = (
        "import jax; ds = jax.devices(); "
        "print('PLATFORMS:' + ','.join(sorted({d.platform for d in ds})))"
    )
    for timeout_s in timeouts:
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout_s,
                cwd=_repo_root(),
            )
        except subprocess.TimeoutExpired:
            continue
        if r.returncode == 0 and "tpu" in r.stdout:
            return True
    return False


def main(on_tpu: bool) -> None:
    import jax
    import numpy as np

    if not on_tpu:
        # belt-and-braces: pin default device to CPU even if some other
        # backend slipped through
        jax.config.update("jax_default_device", jax.devices("cpu")[0])

    from smg_tpu.engine.config import CacheConfig, EngineConfig, SchedulerConfig
    from smg_tpu.engine.engine import Engine
    from smg_tpu.models.config import llama32_1b_config, tiny_test_config
    from smg_tpu.protocols.sampling import SamplingParams

    if on_tpu:
        model_cfg = llama32_1b_config()
        batch, prompt_len, gen_len = 32, 128, 64
        max_seq = 1024
        pages = 32 * (max_seq // 16) + 64
        dtype = "bfloat16"
        horizon = 16
    else:
        model_cfg = tiny_test_config()
        batch, prompt_len, gen_len = 8, 32, 16
        max_seq = 128
        pages = 128
        dtype = "float32"
        horizon = 4

    cfg = EngineConfig(
        model=model_cfg,
        cache=CacheConfig(page_size=16, num_pages=pages, auto_size=False, dtype=dtype),
        scheduler=SchedulerConfig(
            max_batch_size=batch,
            max_seq_len=max_seq,
            max_prefill_tokens=512 if on_tpu else 64,
            prefill_token_buckets=(128, 256, 512) if on_tpu else (32, 64),
            decode_batch_buckets=(batch,),
            decode_horizon=horizon,
        ),
        dtype=dtype,
    )
    engine = Engine(cfg)

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(10, model_cfg.vocab_size - 10, prompt_len).tolist()
        for _ in range(batch)
    ]
    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_len, ignore_eos=True)

    def run_round(tag: str) -> tuple[float, int]:
        finished = set()

        def cb(out):
            if out.finished:
                finished.add(out.rid)

        for i, p in enumerate(prompts):
            engine.submit(p, sp, rid=f"{tag}-{i}", on_output=cb)
        t0 = time.perf_counter()
        start_decode = engine.scheduler.num_decode_tokens
        while len(finished) < batch:
            engine.step()
            if time.perf_counter() - t0 > 600:
                raise TimeoutError(f"bench stuck: {engine.loads()}")
        dt = time.perf_counter() - t0
        return dt, engine.scheduler.num_decode_tokens - start_decode

    run_round("warmup")  # compile
    engine.flush_cache()

    dt, _ = run_round("bench")
    total_new = batch * gen_len
    tput = total_new / dt

    # Roofline accounting: every model step streams the full weights from
    # HBM plus the live KV pages of each active sequence.
    param_bytes = sum(x.nbytes for x in jax.tree.leaves(engine.runner.params))
    kv_itemsize = 2 if dtype == "bfloat16" else 4
    mean_ctx = prompt_len + gen_len / 2
    kv_bytes_per_step = (
        batch
        * mean_ctx
        * model_cfg.num_layers
        * model_cfg.num_kv_heads
        * model_cfg.head_dim
        * 2  # K and V
        * kv_itemsize
    )
    steps_per_sec = tput / batch  # each model step emits `batch` tokens
    hbm_gbps = steps_per_sec * (param_bytes + kv_bytes_per_step) / 1e9
    peak = _HBM_BYTES_PER_SEC["tpu" if on_tpu else "cpu"]
    hbm_util = round(hbm_gbps * 1e9 / peak, 4) if peak else None

    result = {
        "metric": "decode_tokens_per_sec_per_chip"
        if on_tpu
        else "decode_tokens_per_sec_cpu_smoke",
        "value": round(tput, 2),
        "unit": "tok/s",
        "vs_baseline": round(tput / _BASELINE_TOK_S, 3),
        "hbm_gbps": round(hbm_gbps, 2),
        "hbm_util": hbm_util,
        "batch": batch,
        "gen_len": gen_len,
        "param_bytes": param_bytes,
    }
    print(json.dumps(result))


def _salvage_result(stdout) -> bool:
    """Emit the last valid result line from a child's captured stdout, if any.
    A child that completed its measurement but died/stalled in teardown (the
    wedged-plugin scenario) still gets its number recorded."""
    if not stdout:
        return False
    if isinstance(stdout, bytes):
        stdout = stdout.decode(errors="replace")
    for line in reversed(stdout.splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            print(line)
            return True
    return False


def _run_child(mode: str, timeout_s: float) -> bool:
    """Run the benchmark child; forward exactly ONE JSON line from its stdout
    (stderr streams through for progress).  Teardown stalls/crashes after the
    result line are tolerated via _salvage_result."""
    env = dict(os.environ) if mode == "tpu" else _sanitized_env()
    env["SMG_BENCH_MODE"] = mode
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            cwd=_repo_root(),
            timeout=timeout_s,
            stdout=subprocess.PIPE,
            text=True,
        )
    except subprocess.TimeoutExpired as e:
        return _salvage_result(e.stdout)
    return _salvage_result(r.stdout)


if __name__ == "__main__":
    mode = os.environ.get("SMG_BENCH_MODE")
    if mode:
        main(on_tpu=(mode == "tpu"))
        sys.exit(0)
    if _probe_tpu() and _run_child("tpu", timeout_s=1500):
        sys.exit(0)
    sys.exit(0 if _run_child("cpu", timeout_s=900) else 1)
